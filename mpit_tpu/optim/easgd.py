"""EASGD / EAMSGD — elastic-averaging distributed SGD
(reference asyncsgd/optim-eamsgd.lua; mom == 0 gives EASGD, reference :3).

Per sync round (every su-th step, first step included):

1. fetch the center variable w* from the servers (reference :54-57);
2. elastic delta ``sug = mva * (w - w*)`` computed against the *pre-update*
   local w (reference :58-60);
3. push sug as a "gradient" — servers plain-add, i.e. ``w* += mva*(w-w*)``
   (reference :61); the push is *not* waited on: a single ``ping`` overlaps
   it with the local compute (reference :62-64) and it completes during the
   next round's ``wait`` at the latest;
4. the local Nesterov update runs (same math as msgd minus the momentum
   ramp, reference :24-45);
5. ``w -= sug`` pulls the worker toward the center (reference :66).

Between rounds only the local update runs.  TPU-native mechanics: w, vt and
the elastic algebra live in device HBM; the elastic delta and local update
are jitted XLA programs; only w* (in) and sug (out) cross the host boundary,
once per round.

Wire codecs (``MPIT_PS_CODEC``): the elastic push rides the client's GRAD
channel, so with ``int8`` the shipped ``sug`` is block-quantized and the
client's error-feedback residual re-ships each round's quantization error
next round — the center ``w*`` integrates the exact elastic force over
time even though individual pushes are lossy.  The local retract
(``w -= sug``) deliberately uses the *exact* sug: the worker-side
elastic symmetry stays unperturbed, and the center-side difference is
covered by the residual.  Convergence matches the uncompressed run on
the MNIST flagship (tests/test_trainer.py int8 variant).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.obs import get_registry
from mpit_tpu.optim.client_api import ParamClientAPI
from mpit_tpu.optim.msgd import MSGDConfig, msgd_commit, msgd_init, msgd_lookahead


class EAMSGD:
    def __init__(
        self,
        value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
        pclient: ParamClientAPI,
        *,
        lr: float,
        lrd: float = 0.0,
        lrp: float = 0.0,
        mom: float = 0.0,
        l2wd: float = 0.0,
        mva: float = 0.0,  # moving rate alpha (mlaunch uses beta/p = 0.9/6)
        su: int = 1,  # communication period tau
    ):
        if not (su > 0 and mva > 0):
            raise ValueError("eamsgd requires su>0 and mva>0 (reference :86)")
        self.pc = pclient
        self.su = su
        self.mva = mva
        self.dusync = 0.0
        self._started = False
        # Training telemetry (mpit_tpu.obs): the elastic distance
        # ||w - w*|| is EASGD's own convergence signal — the exploration
        # radius the mva force is pulling back.  Derived from the sug
        # host mirror on sync rounds only, and only when obs is enabled
        # (it is an O(n) host reduction).
        _reg = get_registry()
        self._obs = _reg.enabled
        self._m_dist = _reg.gauge("mpit_train_elastic_distance", opt="eamsgd")
        self._m_unorm = _reg.gauge("mpit_train_update_norm", opt="eamsgd")
        # Local rule = msgd without the momentum ramp (reference :24-45).
        cfg = MSGDConfig(lr=lr, lrd=lrd, lrp=lrp, mom=mom, momdecay=0.0, l2wd=l2wd)
        self.cfg = cfg
        self._skip_local = lr == 0.0  # reference :25 guards localupdate on lr~=0

        def _localupdate(w, state, *args):
            w_la, state = msgd_lookahead(w, state, cfg)
            loss, grad = value_and_grad_fn(w_la, *args)
            w_new, state = msgd_commit(w_la, grad, state, cfg)
            return w_new, state, loss

        self._localupdate = jax.jit(_localupdate)
        self._elastic = jax.jit(lambda w, center: self.mva * (w - center))
        self._retract = jax.jit(lambda w, sug: w - sug)
        # Comm-only mode (lr == 0, reference :25): force and retract are
        # adjacent — no local update between — so both ride one fused HBM
        # sweep (ops.fused_update.fused_elastic) when enabled.
        from mpit_tpu.ops.fused_update import fused_elastic, fused_enabled

        self._use_fused_elastic = self._skip_local and fused_enabled(None)
        self._elastic_retract = jax.jit(
            lambda w, center: fused_elastic(w, center, self.mva)
        )

    @property
    def k(self) -> int:
        return int(self.state["k"]) if self._started else 0

    def start(self, w: jnp.ndarray) -> jnp.ndarray:
        self.state = msgd_init(w)
        self._steps = 0  # mirrors state["k"] host-side for the su modulus
        # Dedicated comm copies: recv target for w*, send source for sug
        # (reference :49-53 allocates suw/sug and retargets the client).
        self.center_host = np.zeros_like(np.asarray(w))
        self.sug_host = np.zeros_like(self.center_host)
        self.pc.start(np.array(w), self.sug_host)
        self.pc.reset(self.center_host, self.sug_host)
        self._started = True
        return w

    def step(self, w: jnp.ndarray, *fn_args: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self._started, "call start(w) first"
        sync_round = self._steps % self.su == 0
        w_retracted = None
        if sync_round:
            self.pc.async_recv_param()  # center_host <- w*
            t0 = time.monotonic()
            self.pc.wait()  # completes this recv and any prior send
            self.dusync += time.monotonic() - t0
            if self._use_fused_elastic:
                # One sweep computes sug and the retracted w together.
                w_retracted, sug = self._elastic_retract(
                    w, jnp.asarray(self.center_host)
                )
            else:
                sug = self._elastic(w, jnp.asarray(self.center_host))
            np.copyto(self.sug_host, np.asarray(sug))
            if self._obs:
                # sug = mva * (w - w*): one norm serves both gauges.
                unorm = float(np.linalg.norm(self.sug_host))
                self._m_unorm.set(unorm)
                self._m_dist.set(unorm / self.mva)
            self.pc.async_send_grad()  # server: w* += sug
            t0 = time.monotonic()
            self.pc.ping()  # overlap I/O with local compute (reference :63)
            self.dusync += time.monotonic() - t0

        if self._skip_local:
            loss = jnp.zeros(())
        else:
            w, self.state, loss = self._localupdate(w, self.state, *fn_args)
            self._steps += 1

        if sync_round:
            # w -= mva*(w - w*) (reference :66) — precomputed by the fused
            # sweep in comm-only mode, where no local update intervened.
            w = w_retracted if w_retracted is not None else self._retract(w, sug)
        return w, loss

    def stop(self) -> None:
        if self._started:
            self.pc.wait()  # drain the in-flight elastic push
            self.pc.stop()
