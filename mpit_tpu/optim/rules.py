"""Pure-functional shard-update rules (server-side optimizer math).

In the reference, the parameter server applies an optimizer rule in place to
its HBM^W RAM-resident shard every time a gradient arrives, with per-rule
state tensors allocated next to the shard (reference BiCNN/pserver.lua:50-83
for state allocation, :123-197 for the updates).  Here each rule is a pair
of pure functions

    init(p)              -> state            (a dict-of-arrays pytree)
    apply(p, g, state)   -> (p_new, state_new)

so the server can jit ``apply`` once per shard and reuse it for every
incoming gradient, and single-worker mode can run the *same math* locally
(the reference duplicates it in BiCNN/optim-*-single.lua; here it is one
implementation).

Update math is kept bit-faithful to the reference (including its quirks —
e.g. Adam's ``floor(t/step_div)+1`` bias-correction exponent, Adamax's
``|g|+eps`` inside the max, centered RMSProp with momentum).  All rules are
shape-polymorphic and dtype-preserving; under jit the step counter lives in
the state pytree as a traced scalar.

The sign convention matches the reference wire protocol: clients ship either
pre-scaled updates (``-lr*grad`` for DOWNPOUR, elastic deltas for EASGD) to
be *plain-added*, or raw gradients for the server-side rules to consume.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax.numpy as jnp

State = Dict[str, Any]


class ShardRule(NamedTuple):
    """A (init, apply) pair with hyperparameters already bound."""

    init: Callable[[jnp.ndarray], State]
    apply: Callable[[jnp.ndarray, jnp.ndarray, State], Tuple[jnp.ndarray, State]]


# ---------------------------------------------------------------------------
# plain add — the default rule (reference asyncsgd/pserver.lua:83,
# BiCNN/pserver.lua:197): clients pre-scale, server just accumulates.
# ---------------------------------------------------------------------------


def add_init(p: jnp.ndarray) -> State:
    del p
    return {}


def add_apply(p: jnp.ndarray, g: jnp.ndarray, state: State) -> Tuple[jnp.ndarray, State]:
    return p + g, state


# ---------------------------------------------------------------------------
# centered RMSProp with momentum (reference BiCNN/pserver.lua:123-139)
# ---------------------------------------------------------------------------


def rmsprop_init(p: jnp.ndarray) -> State:
    zeros = jnp.zeros_like(p)
    return {"grad_accum": zeros, "grad_sq_accum": zeros, "update": zeros}


def rmsprop_apply(
    p: jnp.ndarray,
    g: jnp.ndarray,
    state: State,
    *,
    lr: float = 1e-2,
    decay: float = 0.95,
    momentum: float = 0.9,
    epsilon: float = 1e-4,
) -> Tuple[jnp.ndarray, State]:
    grad_accum = decay * state["grad_accum"] + (1.0 - decay) * g
    grad_sq_accum = decay * state["grad_sq_accum"] + (1.0 - decay) * g * g
    # Centered second moment: Var ≈ E[g²] - E[g]² (reference :133-136).
    grad_rms = jnp.sqrt(grad_sq_accum - grad_accum * grad_accum + epsilon)
    update = momentum * state["update"] - lr * g / grad_rms
    return p + update, {
        "grad_accum": grad_accum,
        "grad_sq_accum": grad_sq_accum,
        "update": update,
    }


# ---------------------------------------------------------------------------
# Adam (reference BiCNN/pserver.lua:140-155; single-worker variant
# BiCNN/optim-adam-single.lua:23-32)
# ---------------------------------------------------------------------------


def adam_init(p: jnp.ndarray) -> State:
    zeros = jnp.zeros_like(p)
    return {"t": jnp.zeros((), jnp.int32), "m": zeros, "v": zeros}


def adam_apply(
    p: jnp.ndarray,
    g: jnp.ndarray,
    state: State,
    *,
    lr: float = 1e-3,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
    step_div: int | None = None,
    use_fused: bool | None = None,
) -> Tuple[jnp.ndarray, State]:
    """``step_div`` set -> server-mode bias correction with exponent
    ``floor(t/step_div)+1`` (reference :151-153 — dampens the correction when
    many async clients drive ``t``); None -> plain exponent ``t``
    (single-worker mode, reference optim-adam-single.lua:28-30).

    ``use_fused`` routes the element-wise sweep through the pallas kernel
    (:func:`mpit_tpu.ops.fused_update.fused_adam` — one HBM pass, donated
    buffers); default on on TPU, off elsewhere.  The scalar bias
    correction stays here either way."""
    t = state["t"] + 1
    if step_div is None:
        exponent = t.astype(p.dtype)
    else:
        exponent = (t // step_div + 1).astype(p.dtype)
    beta1_t = 1.0 - jnp.power(jnp.asarray(beta1, p.dtype), exponent)
    beta2_t = 1.0 - jnp.power(jnp.asarray(beta2, p.dtype), exponent)
    lr_t = lr * jnp.sqrt(beta2_t) / beta1_t

    from mpit_tpu.ops.fused_update import fused_adam, fused_enabled

    if p.ndim == 1 and fused_enabled(use_fused):
        p_new, m, v = fused_adam(
            p, g, state["m"], state["v"], lr_t,
            beta1=beta1, beta2=beta2, epsilon=epsilon,
        )
        return p_new, {"t": t, "m": m, "v": v}
    m = beta1 * state["m"] + (1.0 - beta1) * g
    v = beta2 * state["v"] + (1.0 - beta2) * g * g
    d = jnp.sqrt(v) + epsilon
    return p - lr_t * m / d, {"t": t, "m": m, "v": v}


# ---------------------------------------------------------------------------
# Adamax (reference BiCNN/pserver.lua:156-171)
# ---------------------------------------------------------------------------


def adamax_init(p: jnp.ndarray) -> State:
    zeros = jnp.zeros_like(p)
    return {"t": jnp.zeros((), jnp.int32), "m": zeros, "u": zeros}


def adamax_apply(
    p: jnp.ndarray,
    g: jnp.ndarray,
    state: State,
    *,
    lr: float = 2e-3,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
) -> Tuple[jnp.ndarray, State]:
    t = state["t"] + 1
    m = beta1 * state["m"] + (1.0 - beta1) * g
    # Note: epsilon inside the max, on |g| (reference :164-166).
    u = jnp.maximum(beta2 * state["u"], jnp.abs(g) + epsilon)
    beta1_t = 1.0 - jnp.power(jnp.asarray(beta1, p.dtype), t.astype(p.dtype))
    lr_t = lr / beta1_t
    return p - lr_t * m / u, {"t": t, "m": m, "u": u}


# ---------------------------------------------------------------------------
# Adagrad (reference BiCNN/pserver.lua:172-183)
# ---------------------------------------------------------------------------


def adagrad_init(p: jnp.ndarray) -> State:
    return {"t": jnp.zeros((), jnp.int32), "variance": jnp.zeros_like(p)}


def adagrad_apply(
    p: jnp.ndarray,
    g: jnp.ndarray,
    state: State,
    *,
    lr: float = 1e-2,
    lrd: float = 0.0,
    epsilon: float = 1e-10,
) -> Tuple[jnp.ndarray, State]:
    clr = lr / (1.0 + state["t"].astype(p.dtype) * lrd)
    variance = state["variance"] + g * g
    std = jnp.sqrt(variance) + epsilon  # epsilon added post-sqrt (reference :180-181)
    return p - clr * g / std, {"t": state["t"] + 1, "variance": variance}


# ---------------------------------------------------------------------------
# Adadelta (reference BiCNN/pserver.lua:184-195)
# ---------------------------------------------------------------------------


def adadelta_init(p: jnp.ndarray) -> State:
    zeros = jnp.zeros_like(p)
    return {"variance": zeros, "acc_delta": zeros}


def adadelta_apply(
    p: jnp.ndarray,
    g: jnp.ndarray,
    state: State,
    *,
    lr: float = 1.0,
    rho: float = 0.9,
    epsilon: float = 1e-6,
) -> Tuple[jnp.ndarray, State]:
    variance = rho * state["variance"] + (1.0 - rho) * g * g
    std = jnp.sqrt(variance + epsilon)
    delta = jnp.sqrt(state["acc_delta"] + epsilon) / std * g
    acc_delta = rho * state["acc_delta"] + (1.0 - rho) * delta * delta
    return p - lr * delta, {"variance": variance, "acc_delta": acc_delta}


# ---------------------------------------------------------------------------
# Registry — the analog of the reference's optimization-name dispatch
# (BiCNN/pserver.lua:123,140,156,172,184 if/elseif chain).
# ---------------------------------------------------------------------------

_RULES: Dict[str, Tuple[Callable[..., State], Callable[..., Tuple[jnp.ndarray, State]]]] = {
    "add": (add_init, add_apply),
    "rmsprop": (rmsprop_init, rmsprop_apply),
    "adam": (adam_init, adam_apply),
    "adamax": (adamax_init, adamax_apply),
    "adagrad": (adagrad_init, adagrad_apply),
    "adadelta": (adadelta_init, adadelta_apply),
}


#: Per-element optimizer-slot multiplicity of each rule: how many extra
#: vector-shaped state arrays the server allocates beside a shard (scalar
#: step counters are free).  This is the footprint model behind
#: :mod:`mpit_tpu.lm.plan`'s per-server HBM budgeting — a shard of S f32
#: elements under rule R costs ``(1 + STATE_SLOTS[R]) * 4 * S`` bytes —
#: and it is pinned against the real ``init`` shapes in
#: tests/test_optim_rules.py so a new state array cannot silently skew
#: the plan.
STATE_SLOTS: Dict[str, int] = {
    "add": 0,
    "rmsprop": 3,   # grad_accum, grad_sq_accum, update
    "adam": 2,      # m, v (t is scalar)
    "adamax": 2,    # m, u (t is scalar)
    "adagrad": 1,   # variance (t is scalar)
    "adadelta": 2,  # variance, acc_delta
}


def state_slots(name: str) -> int:
    """Vector-shaped state arrays rule ``name`` holds per shard."""
    try:
        return STATE_SLOTS[name]
    except KeyError:
        raise ValueError(
            f"unknown rule {name!r}; have {sorted(_RULES)}") from None


def names() -> Tuple[str, ...]:
    return tuple(_RULES)


def make(name: str, **hyperparams: Any) -> ShardRule:
    """Bind hyperparameters, returning a jit-friendly (init, apply) pair.

    Hyperparameter names are validated eagerly so a typo fails here, at the
    config site, rather than at the first jitted apply."""
    try:
        init, apply = _RULES[name]
    except KeyError:
        raise ValueError(f"unknown rule {name!r}; have {sorted(_RULES)}") from None
    if hyperparams:
        valid = {
            p.name
            for p in inspect.signature(apply).parameters.values()
            if p.kind is inspect.Parameter.KEYWORD_ONLY
        }
        unknown = set(hyperparams) - valid
        if unknown:
            raise ValueError(
                f"rule {name!r} has no hyperparameter(s) {sorted(unknown)}; "
                f"valid: {sorted(valid)}"
            )
        apply = functools.partial(apply, **hyperparams)
    return ShardRule(init=init, apply=apply)
