"""L3 — distributed optimizers.

Three families, mirroring the reference's capability surface (SURVEY.md
section 2, rows 14-18), all expressed TPU-first:

- **Local rules** (:mod:`mpit_tpu.optim.rules`): pure-functional, jittable
  ``init/apply`` shard-update rules — plain-add, RMSProp, Adam, Adamax,
  Adagrad, Adadelta — with exactly the reference's update math (reference
  BiCNN/pserver.lua:123-197).  The *same* functions run on parameter-server
  shards and in single-worker mode; statefulness is an explicit pytree.
- **msgd** (:mod:`mpit_tpu.optim.msgd`): Nesterov momentum SGD with the
  reference's momentum ramp and lr decay (reference asyncsgd/optim-msgd.lua),
  split into lookahead/commit phases so the gradient is evaluated at the
  displaced point, fully under jit.
- **Comm-aware wrappers** (:mod:`mpit_tpu.optim.downpour`,
  :mod:`mpit_tpu.optim.easgd`, :mod:`mpit_tpu.optim.shells`): host-level
  drivers that interleave jitted local math with parameter-server traffic —
  DOWNPOUR (reference asyncsgd/optim-downpour.lua), EASGD/EAMSGD (reference
  asyncsgd/optim-eamsgd.lua), the BiCNN accumulate-and-ship client shells
  (reference BiCNN/optim-*.lua) and the ``*single`` param-push variants
  (reference BiCNN/optim-*-single.lua).
"""

from mpit_tpu.optim import rules
from mpit_tpu.optim.downpour import Downpour
from mpit_tpu.optim.easgd import EAMSGD
from mpit_tpu.optim.msgd import MSGD, msgd_init, msgd_step
from mpit_tpu.optim.shells import RuleShell, SingleWorker

__all__ = [
    "rules",
    "MSGD",
    "msgd_init",
    "msgd_step",
    "Downpour",
    "EAMSGD",
    "RuleShell",
    "SingleWorker",
]
