"""Nesterov momentum SGD ("msgd") — the reference's local update rule.

Semantics follow reference asyncsgd/optim-msgd.lua exactly:

1. optional momentum ramp: ``mom_k = min(mommax, 1 - 0.5/(1 + k/momdecay))``
   (reference :21-23);
2. Sutskever-formulation lookahead: ``vt *= mom_k; w += vt`` *before* the
   gradient is evaluated (reference :24-29) — so the gradient is taken at
   the displaced point;
3. L2 term added to the gradient at the displaced point (reference :31);
4. lr decay ``clr = lr/(1 + k*lrd)^lrp`` (reference :33-35);
5. ``w -= clr*g; vt -= clr*g`` (reference :36-39), step counter ``k += 1``.

TPU-native shape: the whole step — lookahead, loss/grad, commit — is one
pure function suitable for ``jax.jit`` and ``lax.scan`` over minibatches.
The lookahead/commit halves are also exported separately because the
EASGD/EAMSGD wrapper interleaves parameter-server traffic between them
(reference optim-eamsgd.lua:24-45 embeds the same local update).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mpit_tpu.ops.fused_update import fused_enabled as _fused_enabled


class MSGDConfig(NamedTuple):
    lr: float = 0.0
    lrd: float = 0.0  # lr decay
    lrp: float = 0.0  # lr decay power
    mom: float = 0.0
    mommax: float = 1.0
    momdecay: float = 0.0
    l2wd: float = 0.0
    # Reference msgd enables decay only when lrd>0 AND lrp>0
    # (optim-msgd.lua:33); eamsgd's embedded copy uses lrd!=0 AND lrp>0
    # (optim-eamsgd.lua:40) — identical for the sane lrd>=0 regime.
    use_fused: bool | None = None  # pallas commit sweep (on-TPU default)


def msgd_init(w: Any) -> dict:
    return {
        "k": jnp.zeros((), jnp.int32),
        "vt": jax.tree_util.tree_map(jnp.zeros_like, w),
    }


def _effective_momentum(cfg: MSGDConfig, k: jnp.ndarray) -> jnp.ndarray:
    mom = jnp.asarray(cfg.mom, jnp.float32)
    if cfg.mom > 0 and cfg.momdecay > 0:
        mom = jnp.minimum(
            cfg.mommax, 1.0 - 0.5 / (1.0 + k.astype(jnp.float32) / cfg.momdecay)
        )
    return mom


def _effective_lr(cfg: MSGDConfig, k: jnp.ndarray) -> jnp.ndarray:
    clr = jnp.asarray(cfg.lr, jnp.float32)
    if cfg.lrd > 0 and cfg.lrp > 0:
        clr = cfg.lr / jnp.power(1.0 + k.astype(jnp.float32) * cfg.lrd, cfg.lrp)
    return clr


def msgd_lookahead(w: Any, state: dict, cfg: MSGDConfig) -> Tuple[Any, dict]:
    """Phase 1: scale velocity and displace w (reference :24-29)."""
    if cfg.mom <= 0:
        return w, state
    mom = _effective_momentum(cfg, state["k"])
    vt = jax.tree_util.tree_map(lambda v: mom * v, state["vt"])
    w = jax.tree_util.tree_map(jnp.add, w, vt)
    return w, {"k": state["k"], "vt": vt}


def msgd_commit(w: Any, grad: Any, state: dict, cfg: MSGDConfig) -> Tuple[Any, dict]:
    """Phase 2: weight-decay, decayed-lr descent, velocity update (:31-40).

    Flat 1-D params with momentum take the fused pallas sweep
    (:func:`mpit_tpu.ops.fused_update.fused_nesterov_commit`) when enabled
    — one HBM read/write of (w, vt, g) instead of several."""
    clr = _effective_lr(cfg, state["k"])
    if (
        cfg.mom > 0
        and isinstance(w, jnp.ndarray)
        and w.ndim == 1
        and _fused_enabled(cfg.use_fused)
    ):
        from mpit_tpu.ops.fused_update import fused_nesterov_commit

        w_new, vt = fused_nesterov_commit(
            w, state["vt"], grad, clr, l2wd=float(cfg.l2wd)
        )
        return w_new, {"k": state["k"] + 1, "vt": vt}
    if cfg.l2wd != 0:
        grad = jax.tree_util.tree_map(lambda g, p: g + cfg.l2wd * p, grad, w)
    w = jax.tree_util.tree_map(lambda p, g: p - clr * g, w, grad)
    vt = state["vt"]
    if cfg.mom > 0:
        vt = jax.tree_util.tree_map(lambda v, g: v - clr * g, vt, grad)
    return w, {"k": state["k"] + 1, "vt": vt}


def msgd_step(
    value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, Any]],
    w: Any,
    state: dict,
    cfg: MSGDConfig,
    *fn_args: Any,
) -> Tuple[Any, dict, jnp.ndarray]:
    """One full msgd step: lookahead -> grad at displaced w -> commit.

    ``value_and_grad_fn(w, *fn_args) -> (loss, grad)`` is the feval closure
    analog (reference goot.lua:101-126).  Pure; jit the caller.
    """
    w_la, state = msgd_lookahead(w, state, cfg)
    loss, grad = value_and_grad_fn(w_la, *fn_args)
    w_new, state = msgd_commit(w_la, grad, state, cfg)
    return w_new, state, loss


class MSGD:
    """Object wrapper with the same lifecycle as the comm-aware optimizers,
    for uniform dispatch in trainers (reference goot.lua:66-89 dispatch)."""

    def __init__(self, cfg: MSGDConfig, value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, Any]]):
        self.cfg = cfg
        self._step = jax.jit(
            lambda w, state, *args: msgd_step(value_and_grad_fn, w, state, cfg, *args)
        )
        self.state: dict | None = None

    def step(self, w: Any, *fn_args: Any) -> Tuple[Any, jnp.ndarray]:
        if self.state is None:
            self.state = msgd_init(w)
        w, self.state, loss = self._step(w, self.state, *fn_args)
        return w, loss
