"""DOWNPOUR distributed SGD (reference asyncsgd/optim-downpour.lua).

Semantics preserved exactly:

- Every step computes ``dfdx = -(clr) * (grad + l2wd*w)`` with
  ``clr = lr/(1 + k*lrd)`` (reference :22-28,48 — linear decay, no power).
- ``su == 1`` (Hogwild-style): ship ``dfdx`` to the servers (which
  plain-add it) and fetch fresh params every step (reference :46-54).
- ``su > 1``: accumulate ``dfdx``; on every su-th step (k % su == 0,
  checked *before* increment, so the first step syncs) ship the accumulated
  delta and fetch params; between syncs apply ``dfdx`` locally
  (reference :26-45).

TPU-native changes from the reference mechanics (not semantics): the
parameter vector, gradient, and the DOWNPOUR accumulator live in device HBM
and the whole local step (feval + scale + accumulate + local move) is one
jitted XLA program; host<->device transfers happen only on sync steps, and
the host-side buffers the client ships are written with one device->host
copy (the reference instead mutates shared host tensors every step).

Wire codecs (``MPIT_PS_CODEC``): this driver needs no codec awareness —
it writes fp32 deltas into the client's registered ``grad`` mirror and
the ParamClient encodes at ship time.  With the lossy ``int8`` codec the
client's per-shard error-feedback residual folds each sync's
quantization error into the *next* shipped delta, so the server-side sum
of applied updates tracks the true accumulated ``dfdx`` within one
quantization step — the EF-SGD argument that keeps DOWNPOUR's
convergence intact (docs/PROTOCOL.md §error feedback).  The fetched
params are quantized too; su>1 local moves run on the exact local ``w``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.obs import get_registry
from mpit_tpu.optim.client_api import ParamClientAPI


class Downpour:
    """Host driver around a jitted local step and a parameter client."""

    def __init__(
        self,
        value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
        pclient: ParamClientAPI,
        *,
        lr: float,
        lrd: float = 0.0,
        l2wd: float = 0.0,
        su: int = 1,
    ):
        if su < 1:
            raise ValueError("su must be >= 1 (reference asserts pc and su>=1)")
        self.pc = pclient
        self.su = su
        self.k = 0
        self.dusync = 0.0  # blocking-sync seconds (reference state.dusync)
        self._started = False
        # Training telemetry (mpit_tpu.obs): loss + shipped-update norm
        # gauges, written only on sync rounds (where host copies already
        # happen, so no extra device sync) and only when obs is enabled
        # (the norm is an O(n) host reduction).
        _reg = get_registry()
        self._obs = _reg.enabled
        self._m_loss = _reg.gauge("mpit_train_loss", opt="downpour")
        self._m_unorm = _reg.gauge("mpit_train_update_norm", opt="downpour")

        def _local(w, accum, k, *args):
            loss, g = value_and_grad_fn(w, *args)
            if l2wd != 0:
                g = g + l2wd * w
            clr = lr / (1.0 + k.astype(jnp.float32) * lrd) if lrd != 0 else lr
            dfdx = -clr * g
            return loss, dfdx, accum + dfdx, w + dfdx

        self._local = jax.jit(_local)

    def start(self, w: jnp.ndarray) -> jnp.ndarray:
        """Register buffers with the client; first client seeds servers."""
        self.w_host = np.array(w)  # dtype-preserving host mirror
        self.grad_host = np.zeros_like(self.w_host)
        self.accum = jnp.zeros_like(w)
        self.pc.start(self.w_host, self.grad_host)
        self._started = True
        return w

    def _sync(self, payload: jnp.ndarray) -> jnp.ndarray:
        """Ship ``payload`` as the grad, fetch fresh params, time the wait."""
        np.copyto(self.grad_host, np.asarray(payload))
        if self._obs:
            self._m_unorm.set(float(np.linalg.norm(self.grad_host)))
        self.pc.async_send_grad()
        self.pc.async_recv_param()
        t0 = time.monotonic()
        self.pc.wait()
        self.dusync += time.monotonic() - t0
        return jnp.asarray(self.w_host)

    def step(self, w: jnp.ndarray, *fn_args: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self._started, "call start(w) first"
        k = jnp.asarray(self.k, jnp.int32)
        loss, dfdx, accum, w_local = self._local(w, self.accum, k, *fn_args)

        synced = self.su == 1 or self.k % self.su == 0
        if self.su == 1:
            w = self._sync(dfdx)
        elif self.k % self.su == 0:
            w = self._sync(accum)
            self.accum = jnp.zeros_like(accum)
        else:
            self.accum = accum
            w = w_local  # move locally between syncs (reference :44)
        if self._obs and synced:
            self._m_loss.set(float(loss))

        self.k += 1
        return w, loss

    def stop(self) -> None:
        if self._started:
            self.pc.stop()
