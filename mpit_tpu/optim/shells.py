"""Client-side shells for server-stateful rules, and single-worker mode.

**RuleShell** (reference BiCNN/optim-{rmsprop,adam,adamax,adagrad,
adadelta}.lua): in 'global' mode the client ships *raw* gradients — every
step when su==1, else accumulated and shipped on every su-th step — and the
server applies the actual optimizer rule to its shard
(mpit_tpu.optim.rules / reference BiCNN/pserver.lua:123-197).  Between syncs
the local params do not move (reference optim-adam.lua:41 "do nothing
here").  RMSProp additionally has a 'local' mode where the client applies
centered-RMSProp itself and ships the *update* for the server to plain-add
(reference optim-rmsprop.lua:48-65,76-92).

**SingleWorker** (reference BiCNN/optim-*-single.lua, BiCNN/optim-msgd.lua):
one worker runs the full optimizer locally — the same
:mod:`mpit_tpu.optim.rules` math with plain bias correction — then pushes
the whole parameter vector so the server acts as a parameter mirror for the
tester rank (reference optim-adam-single.lua:35-36).

Wire codecs (``MPIT_PS_CODEC``): both shells stay codec-oblivious — they
write fp32 into the client's ``grad`` mirror and the ParamClient
encodes/decodes at the wire.  Error feedback note for ``int8``: in
'global' mode the *raw* gradient stream is what the residual corrects,
which composes with su>1 accumulation (the accumulated delta is shipped
as one frame, its quantization error rides into the next sync).
SingleWorker's whole-param PARAM_PUSH mirror is a state transfer, not an
accumulating signal — it ships without residual, so a lossy codec makes
the mirror (and the tester reading it) approximate to one quantization
step; pick ``none``/``bf16`` there if the tester must match exactly.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.obs import get_registry
from mpit_tpu.optim import rules as rules_mod
from mpit_tpu.optim.client_api import ParamClientAPI
from mpit_tpu.optim.msgd import MSGDConfig, msgd_init, msgd_step


class RuleShell:
    """Accumulate-and-ship client for server-side optimizer rules."""

    def __init__(
        self,
        value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
        pclient: ParamClientAPI,
        *,
        su: int = 1,
        mode: str = "global",
        # 'local'-mode RMSProp hyperparameters (reference optim-rmsprop.lua):
        lr: float = 1e-2,
        decay: float = 0.95,
        momentum: float = 0.9,
        epsilon: float = 1e-4,
    ):
        if su < 1:
            raise ValueError("su must be >= 1")
        if mode not in ("global", "local"):
            raise ValueError(f"mode must be 'global' or 'local', got {mode!r}")
        self.pc = pclient
        self.su = su
        self.mode = mode
        self.k = 0
        self.dusync = 0.0
        self._started = False
        # Training telemetry (mpit_tpu.obs): loss + shipped-update norm,
        # written on sync rounds only and only when obs is enabled (the
        # norm is an O(n) host reduction over the grad mirror).
        _reg = get_registry()
        self._obs = _reg.enabled
        self._m_loss = _reg.gauge("mpit_train_loss", opt=f"rule-{mode}")
        self._m_unorm = _reg.gauge("mpit_train_update_norm",
                                   opt=f"rule-{mode}")
        if mode == "global":
            self._vgf = jax.jit(value_and_grad_fn)

        if mode == "local":
            # Client-side centered RMSProp producing an additive update.
            rule = rules_mod.make(
                "rmsprop", lr=lr, decay=decay, momentum=momentum, epsilon=epsilon
            )

            def _local(w, accum, rstate, *args):
                loss, g = value_and_grad_fn(w, *args)
                w_new, rstate = rule.apply(w, g, rstate)
                update = w_new - w  # the shipped quantity (reference :59-60)
                return loss, update, accum + update, rstate

            self._local = jax.jit(_local)
            self._rule = rule

    def start(self, w: jnp.ndarray) -> jnp.ndarray:
        self.w_host = np.array(w)  # dtype-preserving host mirror
        self.grad_host = np.zeros_like(self.w_host)
        self.accum = jnp.zeros_like(w)
        if self.mode == "local":
            self.rstate = self._rule.init(w)
        self.pc.start(self.w_host, self.grad_host)
        self._started = True
        return w

    def _sync(self, payload: jnp.ndarray) -> jnp.ndarray:
        np.copyto(self.grad_host, np.asarray(payload))
        if self._obs:
            self._m_unorm.set(float(np.linalg.norm(self.grad_host)))
        self.pc.async_send_grad()
        self.pc.async_recv_param()
        t0 = time.monotonic()
        self.pc.wait()
        self.dusync += time.monotonic() - t0
        return jnp.asarray(self.w_host)

    def step(self, w: jnp.ndarray, *fn_args: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self._started, "call start(w) first"
        if self._obs and (self.su == 1 or self.k % self.su == 0):
            synced_loss = True
        else:
            synced_loss = False
        if self.mode == "global":
            loss, g = self._vgf(w, *fn_args)
            if self.su == 1:
                w = self._sync(g)
            else:
                self.accum = self.accum + g
                if self.k % self.su == 0:
                    w = self._sync(self.accum)
                    self.accum = jnp.zeros_like(self.accum)
                # else: params do not move between syncs (reference :41)
        else:  # local-mode RMSProp
            loss, update, accum, self.rstate = self._local(
                w, self.accum, self.rstate, *fn_args
            )
            if self.su == 1:
                w = self._sync(update)
            elif self.k % self.su == 0:
                w = self._sync(accum)
                self.accum = jnp.zeros_like(accum)
            else:
                self.accum = accum
                w = w + update  # move locally (reference :63)
        if synced_loss:
            self._m_loss.set(float(loss))
        self.k += 1
        return w, loss

    def stop(self) -> None:
        if self._started:
            self.pc.stop()


class SingleWorker:
    """Full local optimizer + whole-param push (server as mirror)."""

    def __init__(
        self,
        value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
        pclient: ParamClientAPI,
        *,
        rule: str = "adam",
        **hyperparams: Any,
    ):
        self.pc = pclient
        self._started = False
        if rule == "msgd":
            cfg = MSGDConfig(**hyperparams)

            def _step(w, state, *args):
                return msgd_step(value_and_grad_fn, w, state, cfg, *args)

            self._step_fn = jax.jit(_step)
            self._init_fn = msgd_init
        else:
            # Single-worker bias correction uses the plain exponent t
            # (reference optim-adam-single.lua:28-30), hence step_div=None.
            bound = rules_mod.make(rule, **hyperparams)

            def _step(w, state, *args):
                loss, g = value_and_grad_fn(w, *args)
                w_new, state = bound.apply(w, g, state)
                return w_new, state, loss

            self._step_fn = jax.jit(_step)
            self._init_fn = bound.init

    def start(self, w: jnp.ndarray) -> jnp.ndarray:
        self.state = self._init_fn(w)
        self.w_host = np.array(w)  # dtype-preserving host mirror
        self.grad_host = np.zeros_like(self.w_host)
        self.pc.start(self.w_host, self.grad_host)
        self._started = True
        return w

    def step(self, w: jnp.ndarray, *fn_args: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        assert self._started, "call start(w) first"
        w, self.state, loss = self._step_fn(w, self.state, *fn_args)
        # Push the whole parameter vector (reference optim-adam-single.lua:35-36).
        np.copyto(self.w_host, np.asarray(w))
        self.pc.async_send_param()
        self.pc.wait()
        return w, loss

    def stop(self) -> None:
        if self._started:
            self.pc.stop()
