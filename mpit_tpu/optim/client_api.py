"""The parameter-client protocol the comm-aware optimizers drive.

Mirrors the reference pClient surface (reference asyncsgd/pclient.lua:84-179):
``start/reset`` register host-visible flat buffers, the ``async_*`` calls
enqueue per-server transfer tasks, ``ping`` single-steps I/O to overlap with
compute, ``wait`` drains, ``stop`` runs the shutdown protocol.

The real implementation is :class:`mpit_tpu.ps.client.ParamClient`; optimizer
unit tests substitute an in-process simulator.  Buffers are 1-D numpy arrays
the client slices per server shard (numpy views = the zero-copy analog of
``torch.Storage(grad, offset, size)``, reference pclient.lua:50-52).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

import numpy as np


@runtime_checkable
class ParamClientAPI(Protocol):
    def start(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Register buffers, announce shard offsets to servers, and (first
        client only) seed the servers' shards from ``param``."""

    def reset(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Retarget the transfer buffers (reference pclient.lua:138-151) —
        e.g. EASGD points them at its center/elastic-delta copies."""

    def async_send_grad(self) -> None: ...

    def async_recv_param(self) -> None: ...

    def async_send_param(self) -> None: ...

    def ping(self) -> None:
        """Make one unit of I/O progress without blocking."""

    def wait(self) -> None:
        """Block until all enqueued transfers complete."""

    def stop(self) -> None: ...


@runtime_checkable
class DeviceSyncAPI(ParamClientAPI, Protocol):
    """Optional extension (mpit_tpu.dplane.ExchangeClient): a PS round
    that stays in device memory.  ``sync_device(update)`` ships a flat
    ``jax.Array`` update and returns the refreshed parameter vector as
    a device array — no host mirrors touched for device-eligible
    servers (wire-fallback servers are staged through the mirrors
    transparently).  Trainers should feature-test with
    ``isinstance(pc, DeviceSyncAPI)`` and keep the mirror path as the
    universal fallback."""

    def sync_device(self, update, *, pull: bool = True): ...
