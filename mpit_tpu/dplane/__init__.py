"""mpit_tpu.dplane — the device-resident parameter data plane.

Every pre-dplane hot path round-trips host memory: the server snapshots
its shard d2h, encodes on the host, and ships bytes over TCP/shm; the
client decodes back into a host mirror and re-uploads.  The original
port goal (SNIPPETS.md header) was the opposite — parameters living in
HBM, exchanged over ICI collectives.  This package is that plane:

- :mod:`mpit_tpu.dplane.partition` — a regex -> ``PartitionSpec`` rule
  engine over parameter pytrees (the fmengine ``match_partition_rules``
  shape, SNIPPETS [3]) producing ``NamedSharding``s on a mesh, plus the
  flat-vector layer that subsumes shardctl's weighted cuts: segment
  tables, boundary-aligned cuts, and ``plan_shard_map`` as the layout
  source for versioned shard maps.
- :mod:`mpit_tpu.dplane.hbm` — device-resident shard slots: a shard's
  params and optimizer state live as (optionally mesh-sharded)
  ``jax.Array``s and ``rule.apply`` is jitted with ``donate_argnums``
  so an update never leaves HBM; per-version snapshot (d2h) and pull
  (all-gather) caches keep reads one-copy.
- :mod:`mpit_tpu.dplane.exchange` — the client<->server exchange that
  stays on-device when ranks share a backend (a process-local plane
  registry + backend fingerprints decide) and falls back transparently
  to the framed wire path — codecs, retry/dedup, shard maps all intact
  — across hosts (docs/DEVICE.md has the decision table).
"""

from mpit_tpu.dplane.partition import (
    Segment,
    aligned_cut,
    flat_segments,
    match_partition_rules,
    match_report,
    named_tree_map,
    plan_shard_map,
    tree_shardings,
)
from mpit_tpu.dplane.hbm import (
    HbmSlot,
    PlaneConfig,
    dedupe_state,
    place_flat,
    place_state,
)
from mpit_tpu.dplane.exchange import (
    DevicePlane,
    ExchangeClient,
    ExchangeError,
    backend_fingerprint,
    lookup,
    publish,
    withdraw,
)

__all__ = [
    "Segment", "aligned_cut", "flat_segments", "match_partition_rules",
    "match_report", "named_tree_map", "plan_shard_map", "tree_shardings",
    "HbmSlot", "PlaneConfig", "dedupe_state", "place_flat", "place_state",
    "DevicePlane", "ExchangeClient", "ExchangeError",
    "backend_fingerprint", "lookup", "publish", "withdraw",
]
