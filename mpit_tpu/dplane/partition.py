"""Regex -> PartitionSpec rule engine over parameter pytrees.

The fmengine-style ``match_partition_rules`` shape (SNIPPETS [3]): a
rule table is an ordered sequence of ``(pattern, PartitionSpec)`` pairs;
each leaf's ``/``-joined tree path is matched with ``re.search`` and the
**first** matching rule wins, so every leaf resolves to exactly one
spec.  Two hard invariants, property-tested in tests/test_dplane.py:

- scalar leaves (0-d, or single-element) are never partitioned — they
  resolve to ``PartitionSpec()`` without consuming a rule;
- a non-scalar leaf no rule matches is a loud ``ValueError`` naming the
  leaf (or, opt-in, replicates) — silence here would place a tensor
  wrong and surface as a shape error three layers away.

On top of the per-leaf specs sits the **flat-vector layer** that
subsumes shardctl's weighted cuts as the intra-host story: trainers ship
a single raveled vector (``ravel_pytree``), and the PS cut of that
vector should fall on *parameter boundaries*, not arbitrary offsets —
a shard that splits a weight matrix splits its quantization blocks and
its optimizer-state locality with it.  :func:`flat_segments` renders the
pytree as an ordered segment table, :func:`aligned_cut` cuts the vector
at segment boundaries as close to balanced as the boundaries allow, and
:func:`plan_shard_map` lifts that cut into a versioned
:class:`~mpit_tpu.shardctl.shardmap.ShardMap` — the layout source for
shardctl gangs (``ParamClient(shard_map=...)``).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _key_str(key: Any) -> str:
    """Render one tree-path key the way rule authors write them."""
    for attr in ("key", "idx", "name"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def tree_path_names(tree: Any, sep: str = "/") -> List[str]:
    """The ``sep``-joined path name of every leaf, in tree-leaves order
    (= the ravel_pytree order the flat PS vector uses)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [sep.join(_key_str(k) for k in path) for path, _ in leaves]


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any,
                   sep: str = "/") -> Any:
    """``tree_map`` whose function also receives the leaf's path name."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = [fn(sep.join(_key_str(k) for k in path), leaf)
           for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, out)


def _is_scalar(leaf: Any) -> bool:
    shape = np.shape(leaf)
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules: Sequence[Tuple[str, PartitionSpec]],
                          tree: Any, *, sep: str = "/",
                          on_unmatched: str = "raise") -> Any:
    """A pytree of ``PartitionSpec``, one per leaf of ``tree``.

    ``rules`` is ordered; the first pattern ``re.search``-matching the
    leaf's path name wins.  Scalars always resolve to ``P()``.
    ``on_unmatched``: ``"raise"`` (default) or ``"replicate"``.
    """
    if on_unmatched not in ("raise", "replicate"):
        raise ValueError(
            f"on_unmatched must be 'raise' or 'replicate', got "
            f"{on_unmatched!r}")

    def pick(name: str, leaf: Any) -> PartitionSpec:
        if _is_scalar(leaf):
            return PartitionSpec()
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                return spec
        if on_unmatched == "replicate":
            return PartitionSpec()
        raise ValueError(
            f"no partition rule matches leaf {name!r} "
            f"(shape {np.shape(leaf)}); add a rule or a catch-all "
            "('.*', P()) tail")

    return named_tree_map(pick, tree, sep=sep)


def match_report(rules: Sequence[Tuple[str, PartitionSpec]], tree: Any,
                 *, sep: str = "/") -> Dict[str, int]:
    """Which rule index claimed each leaf: ``{leaf name: rule index}``,
    with ``-1`` for scalar leaves (never partitioned) and ``-2`` for
    unmatched ones.  The audit surface behind the engine: a leaf appears
    exactly once (tree paths are unique), and tests assert every
    non-scalar leaf resolved to exactly one live rule."""
    report: Dict[str, int] = {}

    def pick(name: str, leaf: Any) -> int:
        if _is_scalar(leaf):
            idx = -1
        else:
            idx = -2
            for i, (pattern, _spec) in enumerate(rules):
                if re.search(pattern, name) is not None:
                    idx = i
                    break
        report[name] = idx
        return idx

    named_tree_map(pick, tree, sep=sep)
    return report


def _spec_axes(spec: PartitionSpec):
    """Per-dimension tuples of mesh axis names (PartitionSpec entries
    may be a name, a tuple of names, or None)."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    return out


def validate_spec(mesh: Mesh, spec: PartitionSpec, shape: Tuple[int, ...],
                  name: str = "<leaf>") -> None:
    """Loudly reject a spec the mesh cannot realize for ``shape``: an
    unknown axis name, more partitioned dims than the leaf has, or a dim
    not divisible by its axis-size product."""
    axes = _spec_axes(spec)
    if len(axes) > len(shape):
        raise ValueError(
            f"spec {spec} for {name!r} names {len(axes)} dims but the "
            f"leaf has shape {shape}")
    seen: set = set()
    for dim, dim_axes in enumerate(axes):
        factor = 1
        for ax in dim_axes:
            if ax not in mesh.shape:
                raise ValueError(
                    f"spec {spec} for {name!r} uses axis {ax!r} not in "
                    f"mesh axes {tuple(mesh.shape)}")
            if ax in seen:
                raise ValueError(
                    f"spec {spec} for {name!r} repeats mesh axis {ax!r}")
            seen.add(ax)
            factor *= mesh.shape[ax]
        if factor > 1 and shape[dim] % factor:
            raise ValueError(
                f"dim {dim} of {name!r} (shape {shape}) is not divisible "
                f"by mesh factor {factor} for spec {spec}")


def tree_shardings(mesh: Mesh, specs: Any, tree: Optional[Any] = None,
                   *, sep: str = "/", naive_fallback: bool = False) -> Any:
    """Lift a spec pytree into ``NamedSharding``s on ``mesh``.

    With ``tree`` given, every spec is validated against its leaf's
    shape; ``naive_fallback=True`` degrades an indivisible dim to
    unpartitioned (the SNIPPETS [2] naive-sharding behavior) instead of
    raising — axis-name errors always raise."""
    if tree is None:
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    names = iter(tree_path_names(tree, sep=sep))

    def lift(spec: PartitionSpec, leaf: Any) -> NamedSharding:
        name = next(names)
        shape = np.shape(leaf)
        if naive_fallback:
            entries = []
            for dim, dim_axes in enumerate(_spec_axes(spec)):
                factor = 1
                for ax in dim_axes:
                    if ax not in mesh.shape:
                        raise ValueError(
                            f"spec {spec} for {name!r} uses axis {ax!r} "
                            f"not in mesh axes {tuple(mesh.shape)}")
                    factor *= mesh.shape[ax]
                ok = factor == 1 or (dim < len(shape)
                                     and shape[dim] % factor == 0)
                entries.append(spec[dim] if ok else None)
            spec = PartitionSpec(*entries)
        validate_spec(mesh, spec, shape, name)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(
        lift, specs, tree, is_leaf=lambda x: isinstance(x, PartitionSpec))


def shard_tree(tree: Any, shardings: Any) -> Any:
    """``device_put`` every leaf with its sharding (host -> HBM)."""
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)


# ---------------------------------------------------------------------------
# flat-vector layer: segment tables + boundary-aligned cuts
# ---------------------------------------------------------------------------


class Segment(NamedTuple):
    """One leaf's extent inside the raveled flat vector."""

    name: str
    offset: int
    size: int

    @property
    def end(self) -> int:
        return self.offset + self.size


def flat_segments(tree: Any, sep: str = "/") -> List[Segment]:
    """The ordered segment table of ``ravel_pytree(tree)``: one entry
    per leaf, contiguous, in tree-leaves order (the order ravel uses)."""
    segments: List[Segment] = []
    offset = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        name = sep.join(_key_str(k) for k in path)
        segments.append(Segment(name, offset, size))
        offset += size
    return segments


def aligned_cut(plong: int, segments: Sequence[Segment], n: int,
                weights: Optional[Sequence[float]] = None):
    """Cut ``[0, plong)`` into ``n`` contiguous shards whose interior
    boundaries fall on segment boundaries, each as close to the equal
    cut ``i*plong/n`` as the boundaries allow.

    ``weights`` (optional, one positive number per shard) replaces the
    equal targets with cumulative-fraction targets
    ``sum(weights[:i]) / sum(weights) * plong`` — the aligned-cut
    counterpart of :func:`mpit_tpu.ps.sharding.weighted_layout`.  Shard
    ``i`` lands as close to ``weights[i] / sum(weights)`` of the vector
    as the parameter boundaries allow; the :mod:`mpit_tpu.lm` plan uses
    this to equalize *bytes held per server* (params + optimizer slots)
    when server budgets differ.

    Invariants (property-tested): shards tile ``[0, plong)``, every
    shard is nonempty, every interior cut is some segment's offset, and
    the result is a pure function of its arguments.  Raises when fewer
    segments than shards exist — an element-level cut would split a
    parameter, which is exactly what alignment is for (fall back to
    :func:`mpit_tpu.ps.sharding.shard_layout` deliberately instead).
    """
    from mpit_tpu.ps.sharding import Shard

    if n < 1:
        raise ValueError("need at least one shard")
    if weights is not None:
        w = [float(x) for x in weights]
        if len(w) != n:
            raise ValueError(f"weights has {len(w)} entries for {n} shards")
        if any(x <= 0 for x in w):
            raise ValueError("weights must be positive")
        total = sum(w)
        targets = []
        acc = 0.0
        for x in w[:-1]:
            acc += x
            targets.append(acc / total * plong)
    else:
        targets = [i * plong / n for i in range(1, n)]
    segs = sorted(segments, key=lambda s: s.offset)
    pos = 0
    for s in segs:
        if s.offset != pos or s.size <= 0:
            raise ValueError(
                f"segments must tile [0, plong) contiguously; {s.name!r} "
                f"covers [{s.offset}, {s.end}) but {pos} elements are "
                "assigned so far")
        pos = s.end
    if pos != plong:
        raise ValueError(f"segments cover {pos} of {plong} elements")
    if len(segs) < n:
        raise ValueError(
            f"cannot align {n} shards on {len(segs)} segments — an "
            "aligned cut never splits a parameter (use shard_layout for "
            "element-level cuts)")
    boundaries = [s.offset for s in segs[1:]]  # interior candidates
    cuts: List[int] = []
    lo = 0
    for i in range(1, n):
        target = targets[i - 1]
        # Leave enough boundaries for the remaining n-1-i cuts.
        hi = len(boundaries) - (n - 1 - i)
        window = boundaries[lo:hi]
        best = min(range(len(window)),
                   key=lambda j: (abs(window[j] - target), window[j]))
        cuts.append(window[best])
        lo += best + 1
    edges = [0] + cuts + [plong]
    return [Shard(edges[i], edges[i + 1] - edges[i]) for i in range(n)]


def plan_shard_map(tree: Any, server_ranks: Sequence[int], *,
                   sep: str = "/", shards_per_server: int = 1,
                   weights: Optional[Sequence[float]] = None):
    """A version-0 :class:`~mpit_tpu.shardctl.shardmap.ShardMap` whose
    cut is segment-aligned — the partition engine acting as shardctl's
    layout source.  ``shards_per_server`` over-partitions (the §9.1
    elasticity units) while keeping every cut on a parameter boundary.
    ``weights`` (one per server) skews the cut targets; a server's
    weight is spread evenly over its ``shards_per_server`` shards.
    Pass the result to ``ParamClient(shard_map=...)``."""
    from mpit_tpu.shardctl.shardmap import ShardMap

    ranks = list(server_ranks)
    if not ranks:
        raise ValueError("need at least one server rank")
    k = max(int(shards_per_server), 1)
    segments = flat_segments(tree, sep=sep)
    plong = segments[-1].end
    cut_weights = None
    if weights is not None:
        if len(weights) != len(ranks):
            raise ValueError(
                f"weights has {len(list(weights))} entries for "
                f"{len(ranks)} servers")
        cut_weights = [float(w) / k for w in weights for _ in range(k)]
    shards = aligned_cut(plong, segments, len(ranks) * k,
                         weights=cut_weights)
    owners = [r for r in ranks for _ in range(k)]
    return ShardMap.from_shards(shards, owners)
