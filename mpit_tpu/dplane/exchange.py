"""The device exchange: client<->server param traffic that stays in HBM.

Topology decision, made once per (client, server) pair at ``start``:

- the server published a :class:`DevicePlane` in this process's plane
  registry, its backend fingerprint matches the client's, the codec is
  identity, and the gang is on the static shard cut  ==>  **device
  path**: ops are submitted straight to the server's plane queue and
  executed by the server's own service task against its
  :class:`~mpit_tpu.dplane.hbm.HbmSlot` — grads ride as ``jax.Array``s,
  pulls return the slot's per-version replicated array (an all-gather,
  never a d2h), and delivery is exactly-once by construction (an
  in-process queue cannot drop, duplicate, or reorder);
- anything else  ==>  **wire fallback**: the op runs through the inner
  :class:`~mpit_tpu.ps.client.ParamClient` completely unchanged —
  codecs, [epoch, seq] framing, retry/dedup, NACK re-routing, shard
  maps all intact.  docs/DEVICE.md §3 is the normative decision table;
  docs/PROTOCOL.md §10 pins the boundary.

The protocol wire is **always** live even for all-device gangs: INIT,
seeding, heartbeats and STOP ride it, so lease/eviction semantics and
the stop protocol are identical in every mode — the device path is a
data-plane bypass, not a second protocol.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from mpit_tpu.obs import registry_or_local
from mpit_tpu.utils.logging import get_logger


class ExchangeError(RuntimeError):
    """A device-path op failed terminally (server stopped / timed out).
    The never-hang analog of RetryExhausted for the in-process plane."""


def backend_fingerprint(devices=None) -> Tuple[int, str]:
    """(pid, platform) — two ranks share a backend when both match.
    Process identity is what makes the in-process queue sound; platform
    identity is what makes device arrays from one side consumable by
    the other without a host hop."""
    if devices:
        platform = devices[0].platform
    else:
        from mpit_tpu.utils.platform import default_devices

        platform = default_devices()[0].platform
    return (os.getpid(), platform)


# ---------------------------------------------------------------------------
# the process-local plane registry (the rendezvous for the device path)


_registry: Dict[Tuple[str, int], "DevicePlane"] = {}
_registry_lock = threading.Lock()


def publish(rank: int, plane: "DevicePlane", namespace: str = "") -> None:
    with _registry_lock:
        _registry[(namespace, rank)] = plane


def withdraw(rank: int, namespace: str = "") -> None:
    with _registry_lock:
        _registry.pop((namespace, rank), None)


def lookup(rank: int, namespace: str = "") -> "Optional[DevicePlane]":
    with _registry_lock:
        return _registry.get((namespace, rank))


class DeviceTicket:
    """One submitted device op; the client blocks on ``event``."""

    __slots__ = ("kind", "crank", "srank", "payload", "event", "result",
                 "error")

    def __init__(self, kind: str, crank: int, srank: int, payload=None):
        self.kind = kind  # 'grad' | 'push' | 'pull' | 'pull_dev'
        self.crank = crank
        self.srank = srank
        self.payload = payload
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None


class DevicePlane:
    """A server's published device-exchange endpoint: a FIFO ticket
    queue drained by the server's own scheduler task, so device ops
    serialize with wire ops under the server's single-writer
    discipline (serve-latest-committed, no torn state)."""

    def __init__(self, rank: int, fingerprint: Tuple[int, str]):
        self.rank = rank
        self.fingerprint = fingerprint
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._closed: Optional[str] = None

    def submit(self, ticket: DeviceTicket) -> DeviceTicket:
        with self._lock:
            if self._closed is not None:
                raise ExchangeError(
                    f"device plane of server {self.rank} is closed "
                    f"({self._closed})")
            self._q.append(ticket)
        return ticket

    def pop(self) -> Optional[DeviceTicket]:
        with self._lock:
            return self._q.popleft() if self._q else None

    def close(self, reason: str) -> None:
        """Terminal: fail every queued ticket loudly — a client blocked
        on a stopped server's plane must raise, never hang."""
        with self._lock:
            self._closed = reason
            pending = list(self._q)
            self._q.clear()
        for t in pending:
            t.error = ExchangeError(
                f"server {self.rank} stopped before serving the "
                f"{t.kind} op ({reason})")
            t.event.set()

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)


# ---------------------------------------------------------------------------
# client side


class ExchangeClient:
    """ParamClientAPI front for a :class:`ParamClient` that routes each
    server's data ops over the device path when eligible and the wire
    otherwise.  Drop-in for the comm-aware optimizers: they keep writing
    the host mirrors; :meth:`sync_device` is the extra, fully
    device-resident round for trainers that hold ``jax.Array``s."""

    def __init__(self, inner, *, device_ranks: Optional[Sequence[int]] = None,
                 namespace: str = "", require_device: bool = False):
        self.pc = inner
        self.namespace = namespace
        self._forced = list(device_ranks) if device_ranks is not None else None
        self._require = require_device
        self._planes: Dict[int, DevicePlane] = {}
        self._pending: List[DeviceTicket] = []
        self.log = get_logger("dplane", inner.rank)
        _m = registry_or_local()
        self._m_dev_ranks = _m.gauge("mpit_dplane_device_ranks",
                                     rank=inner.rank)
        self._m_wire_ranks = _m.gauge("mpit_dplane_wire_fallback_ranks",
                                      rank=inner.rank)
        self._m_ops = {
            "device": _m.counter("mpit_dplane_exchange_ops_total",
                                 rank=inner.rank, path="device"),
            "wire": _m.counter("mpit_dplane_exchange_ops_total",
                               rank=inner.rank, path="wire"),
        }

    # -- mirrors (honor inner.reset retargets) -------------------------------

    @property
    def param(self) -> np.ndarray:
        return self.pc.param

    @property
    def grad(self) -> np.ndarray:
        return self.pc.grad

    @property
    def device_ranks(self) -> List[int]:
        return sorted(self._planes)

    # -- lifecycle -----------------------------------------------------------

    def start(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Full wire handshake first (INIT + seeding are protocol, not
        data), then resolve which servers are device-eligible."""
        self.pc.start(param, grad)
        self._resolve()

    def _resolve(self) -> None:
        self._planes.clear()
        fp = backend_fingerprint()
        eligible = self.pc.codec.identity and not getattr(
            self.pc, "_sc", False)
        for srank in self.pc.sranks:
            if self._forced is not None and srank not in self._forced:
                continue
            plane = lookup(srank, self.namespace)
            if plane is not None and eligible and plane.fingerprint == fp:
                self._planes[srank] = plane
        if self._forced is not None:
            missing = set(self._forced) - set(self._planes)
            if missing:
                raise ExchangeError(
                    f"device_ranks {sorted(missing)} are not "
                    "device-eligible (no published plane, fingerprint "
                    "mismatch, non-identity codec, or shardctl mode)")
        if self._require and len(self._planes) < len(self.pc.sranks):
            wire = sorted(set(self.pc.sranks) - set(self._planes))
            raise ExchangeError(
                f"require_device: servers {wire} fell back to the wire")
        self._m_dev_ranks.set(len(self._planes))
        self._m_wire_ranks.set(len(self.pc.sranks) - len(self._planes))
        if self._planes:
            self.log.info(
                "device exchange to servers %s (wire fallback: %s)",
                self.device_ranks,
                sorted(set(self.pc.sranks) - set(self._planes)))

    def reset(self, param: np.ndarray, grad: np.ndarray) -> None:
        self.pc.reset(param, grad)

    def _deadline_s(self) -> float:
        ft = self.pc.ft
        if ft.op_deadline_s > 0:
            return ft.op_deadline_s * (ft.max_retries + 1) + 5.0
        return 60.0

    def _submit(self, srank: int, kind: str, payload=None) -> None:
        ticket = DeviceTicket(kind, self.pc.rank, srank, payload)
        self._planes[srank].submit(ticket)
        self._pending.append(ticket)
        self._m_ops["device"].inc()

    # -- ParamClientAPI ------------------------------------------------------

    def async_send_grad(self) -> None:
        for srank, shard in zip(self.pc.sranks, self.pc.shards):
            if srank in self._planes:
                # Submit-time copy onto the device == the wire path's
                # encode-at-ship staging: the optimizer may rewrite the
                # mirror the moment wait() returns.
                view = self.grad[shard.offset:shard.end]
                self._submit(srank, "grad", jax.numpy.asarray(view))
            else:
                self._m_ops["wire"].inc()
                self.pc.enqueue_wire_op(
                    srank, self.pc._send_grad(srank, shard), "send_grad")

    def async_recv_param(self) -> None:
        for srank, shard in zip(self.pc.sranks, self.pc.shards):
            if srank in self._planes:
                self._submit(srank, "pull")
            else:
                self._m_ops["wire"].inc()
                self.pc.enqueue_wire_op(
                    srank, self.pc._recv_param(srank, shard), "recv_param")

    def async_send_param(self) -> None:
        for srank, shard in zip(self.pc.sranks, self.pc.shards):
            if srank in self._planes:
                view = self.param[shard.offset:shard.end]
                self._submit(srank, "push", jax.numpy.asarray(view))
            else:
                self._m_ops["wire"].inc()
                self.pc.enqueue_wire_op(
                    srank, self.pc._send_param(srank, shard), "send_param")

    def ping(self, n: int = 1) -> None:
        self.pc.ping(n)

    def wait(self) -> None:
        """Drain the wire, then the device tickets.  A pull ticket's
        result is the slot's per-version host snapshot — written into
        the registered param mirror exactly where the wire path would
        decode it."""
        self.pc.wait()
        pending, self._pending = self._pending, []
        deadline = self._deadline_s()
        shard_of = dict(zip(self.pc.sranks, self.pc.shards))
        for ticket in pending:
            if not ticket.event.wait(deadline):
                raise ExchangeError(
                    f"device {ticket.kind} op timed out after "
                    f"{deadline:.1f}s (server service stalled?)")
            if ticket.error is not None:
                raise ticket.error
            if ticket.kind == "pull":
                shard = shard_of[ticket.srank]
                self.param[shard.offset:shard.end] = ticket.result

    def stop(self) -> None:
        self.pc.stop()

    def residual_norm(self) -> float:
        return self.pc.residual_norm()

    @property
    def retries(self) -> int:
        return self.pc.retries

    # -- the fully device-resident round ------------------------------------

    def sync_device(self, update, *, pull: bool = True,
                    concat: bool = True):
        """One PS round that never touches the host for device-eligible
        servers.  ``update`` is either one flat ``jax.Array`` (sliced
        per shard on device) or a per-shard list of device arrays — the
        sharded-native form a TPU loop holds anyway, which skips the
        slice entirely.  Refreshed params come back as one concatenated
        vector (``concat=True``) or the per-shard list (``concat=False``
        — again the zero-extra-copy sharded form).  Wire-fallback
        servers are staged through the host mirrors by
        :meth:`_stage_wire_host` (the one sanctioned host hop, and only
        for those ranks)."""
        parts_in = isinstance(update, (list, tuple))
        if parts_in and len(update) != len(self.pc.shards):
            raise ValueError(
                f"{len(update)} update parts for {len(self.pc.shards)} "
                "shards")
        wire_ranks = [s for s in self.pc.sranks if s not in self._planes]
        if wire_ranks:
            self._stage_wire_host(update, wire_ranks, parts_in)
        for idx, (srank, shard) in enumerate(
                zip(self.pc.sranks, self.pc.shards)):
            if srank in self._planes:
                g = (update[idx] if parts_in
                     else update[shard.offset:shard.end])
                self._submit(srank, "grad", g)
                if pull:
                    self._submit(srank, "pull_dev")
        if not pull:
            self.wait()
            return None
        self.pc.wait()
        pending, self._pending = self._pending, []
        deadline = self._deadline_s()
        pulls: Dict[int, Any] = {}
        for ticket in pending:
            if not ticket.event.wait(deadline):
                raise ExchangeError(
                    f"device {ticket.kind} op timed out after "
                    f"{deadline:.1f}s (server service stalled?)")
            if ticket.error is not None:
                raise ticket.error
            if ticket.kind == "pull_dev":
                pulls[ticket.srank] = ticket.result
        parts = []
        for srank, shard in zip(self.pc.sranks, self.pc.shards):
            if srank in pulls:
                parts.append(pulls[srank])
            else:
                parts.append(jax.numpy.asarray(
                    self.param[shard.offset:shard.end]))
        if not concat:
            return parts
        return jax.numpy.concatenate(parts) if len(parts) > 1 else parts[0]

    def _stage_wire_host(self, update, wire_ranks: List[int],
                         parts_in: bool = False) -> None:
        """Materialize the wire-fallback ranks' updates once and run
        their framed send+recv ops — fully inside the existing
        retry/dedup machinery."""
        host = None if parts_in else np.asarray(update)
        for idx, (srank, shard) in enumerate(
                zip(self.pc.sranks, self.pc.shards)):
            if srank in wire_ranks:
                self.grad[shard.offset:shard.end] = (
                    np.asarray(update[idx]) if parts_in
                    else host[shard.offset:shard.end])
                self._m_ops["wire"].inc()
                self.pc.enqueue_wire_op(
                    srank, self.pc._send_grad(srank, shard), "send_grad")
                self.pc.enqueue_wire_op(
                    srank, self.pc._recv_param(srank, shard), "recv_param")
