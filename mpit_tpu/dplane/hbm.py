"""Device-resident shard slots — params and optimizer state stay in HBM.

An :class:`HbmSlot` is the device-side body of one PS shard: the
parameter slice and its rule (optimizer) state live as ``jax.Array``s —
optionally sharded over a mesh axis — and every update runs one jitted
``decode + rule.apply`` XLA program compiled with ``donate_argnums`` on
the param and state, so the update writes back into the same HBM
footprint instead of reallocating it (the MT-J303 contract, now load
bearing: a donated buffer is deleted, which tests assert).

Reads are cached per committed version, mirroring the PR 2 snapshot
cache on both sides of the host boundary:

- :meth:`HbmSlot.snapshot_host` — ONE device->host copy per version
  (the wire path's d2h; name carries ``host`` on purpose: it is the
  only sanctioned host materialization in this module — mtlint
  MT-J311);
- :meth:`HbmSlot.pull_device` — ONE replicate program per version: a
  jitted identity with replicated ``out_shardings``, which XLA lowers
  to an all-gather over the shard axis.  The result is a *fresh* buffer
  (never an alias of the param), so a later donated apply cannot delete
  an array a puller still holds.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from mpit_tpu.obs import registry_or_local
from mpit_tpu.optim.rules import ShardRule


@dataclasses.dataclass(frozen=True)
class PlaneConfig:
    """How a server places + serves its device-resident shards.

    ``mesh=None`` places on the default device unsharded; with a mesh,
    flat vectors shard over ``axis`` when divisible (else replicate —
    the naive fallback, never an error).  ``publish=False`` keeps the
    slots device-resident without offering the in-process exchange
    (``namespace`` isolates concurrent gangs in one process)."""

    mesh: Optional[Mesh] = None
    axis: str = "shard"
    donate: bool = True
    publish: bool = True
    namespace: str = ""

    @classmethod
    def auto(cls, **kw) -> "PlaneConfig":
        """Mesh over every default device when more than one exists
        (all on the shard axis), else single-device placement."""
        from mpit_tpu.parallel.mesh import make_mesh
        from mpit_tpu.utils.platform import default_devices

        devs = default_devices()
        mesh = make_mesh(devs, dp=1) if len(devs) > 1 else None
        return cls(mesh=mesh, **kw)


def flat_sharding(cfg: PlaneConfig, size: int) -> Optional[NamedSharding]:
    """The sharding a flat ``(size,)`` vector gets under ``cfg``."""
    if cfg.mesh is None:
        return None
    n = cfg.mesh.shape[cfg.axis]
    spec = PartitionSpec(cfg.axis) if size % n == 0 else PartitionSpec()
    return NamedSharding(cfg.mesh, spec)


def place_flat(arr, cfg: Optional[PlaneConfig]):
    """Place a flat vector per ``cfg`` (plain ``jnp.asarray`` when no
    plane is configured) — the one placement helper every dplane call
    site shares, so server/shardctl/exchange cannot disagree."""
    if cfg is None:
        return jnp.asarray(arr)
    sharding = flat_sharding(cfg, int(np.shape(arr)[0]))
    if sharding is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, sharding)


_identity_copy = None


def device_copy(x):
    """A bit-exact fresh *device-owned* buffer for ``x`` (jitted
    identity: jax/XLA never alias an un-donated output to its input,
    verified by the donation tests).  Stays on device; preserves
    sharding.  Also the donation-safety helper: ``jnp.asarray`` of an
    aligned numpy array ALIASES the numpy memory on the CPU backend,
    and donating a numpy-backed buffer hands XLA memory it does not
    own — heap corruption, observed as flaky aborts.  Everything that
    enters a donated apply chain must pass through here first."""
    global _identity_copy
    if _identity_copy is None:
        _identity_copy = jax.jit(lambda v: v)
    return _identity_copy(x)


#: back-compat internal alias
_device_copy = device_copy


def dedupe_state(state):
    """Break buffer aliasing inside a rule-state dict: several rules
    init multiple entries from ONE ``zeros_like`` array (e.g. Adam's m
    and v), which a donated apply would donate twice — an XLA error.
    Aliased leaves get a fresh device copy; distinct leaves pass
    through untouched."""
    seen: set = set()
    out = {}
    for k, v in (state or {}).items():
        if id(v) in seen:
            v = _device_copy(v)
        seen.add(id(v))
        out[k] = v
    return out


def place_state(state, cfg: Optional[PlaneConfig]):
    """Place a rule-state pytree next to its param: flat arrays follow
    the param's sharding, scalars replicate.  Always de-aliased — see
    :func:`dedupe_state` — and numpy-backed leaves are re-owned on
    device (:func:`device_copy`): restored/migrated state feeds
    donated applies, which must never consume numpy-owned memory."""
    def own(v):
        placed = jnp.asarray(v)
        return device_copy(placed) if isinstance(v, np.ndarray) else placed

    if cfg is None or cfg.mesh is None:
        return dedupe_state(
            {k: own(v) for k, v in (state or {}).items()})

    def put(v):
        shape = np.shape(v)
        if len(shape) == 1:
            return place_flat(v, cfg)
        return jax.device_put(
            v, NamedSharding(cfg.mesh, PartitionSpec()))

    return dedupe_state({k: put(v) for k, v in (state or {}).items()})


class HbmSlot:
    """One device-resident shard: param + rule state + versioned caches."""

    def __init__(self, size: int, rule: ShardRule, dtype=np.float32, *,
                 config: Optional[PlaneConfig] = None, rank: int = -1):
        self.size = int(size)
        self.rule = rule
        self.dtype = np.dtype(dtype)
        self.config = config or PlaneConfig()
        self.rank = rank
        # device_copy: place_flat aliases the aligned numpy zeros on
        # the CPU backend, and the donated applies must never consume
        # numpy-backed memory (use-after-free once the alias's base
        # drops — see device_copy).
        self.param = device_copy(
            place_flat(np.zeros(self.size, self.dtype), self.config))
        self.rule_state = dedupe_state(rule.init(self.param))
        #: committed version: bumps on every apply/seed (the snapshot
        #: cache key, same meaning as the server's _snap_version)
        self.version = 0
        self._fused: Dict[Optional[str], Callable] = {}
        self._snap_host: Optional[Tuple[int, np.ndarray]] = None
        self._pull_cache: Optional[Tuple[int, Any]] = None
        self._replicate: Optional[Callable] = None
        _m = registry_or_local()
        self._m_applies = _m.counter("mpit_dplane_device_applies_total",
                                     rank=rank)
        self._m_copies = _m.counter("mpit_dplane_snapshot_copies_total",
                                    rank=rank)
        self._m_gathers = _m.counter("mpit_dplane_pull_gathers_total",
                                     rank=rank)
        self._m_bytes = _m.gauge("mpit_dplane_hbm_bytes", rank=rank)
        self._m_bytes.set(self.size * self.dtype.itemsize)

    # -- write path: one donated XLA program per update ---------------------

    def _fused_apply(self, codec=None) -> Callable:
        """The jitted update for one codec (None = device-native grads):
        frame decode fused with ``rule.apply``, param + state donated —
        the whole update is one XLA call that never leaves HBM."""
        key = codec.name if codec is not None else None
        fn = self._fused.get(key)
        if fn is None:
            rule_apply = self.rule.apply
            if codec is None or codec.identity:
                body = rule_apply
            else:
                size = self.size

                def body(param, parts, state):
                    return rule_apply(param, codec.decode_parts(parts, size),
                                      state)

            donate = (0, 2) if self.config.donate else ()
            fn = jax.jit(body, donate_argnums=donate)
            self._fused[key] = fn
        return fn

    def _fused_chunk_apply(self, codec, csize: int) -> Callable:
        """The jitted per-chunk update for streamed transfers
        (docs/PROTOCOL.md §12): decode the chunk frame, slice the
        ``csize`` window out of param + every (param-shaped) state
        leaf, run ``rule.apply`` on the slices, write both back with
        ``dynamic_update_slice`` — one donated XLA call per chunk, so
        the update of chunk k runs while chunk k+1 is on the wire.
        ``lo`` is a traced scalar: one compiled program per (codec,
        chunk size), not per offset.  Bit-equality to the whole-shard
        apply holds exactly because every supported rule is
        element-wise over (param, grad, state) — the server's
        negotiation rejects chunking for rules with scalar state."""
        key = (codec.name if codec is not None else None, csize)
        fn = self._fused.get(("chunk",) + key)
        if fn is None:
            rule_apply = self.rule.apply

            def body(param, payload, state, lo):
                g = (payload if codec is None or codec.identity
                     else codec.decode_parts(payload, csize))
                psl = jax.lax.dynamic_slice(param, (lo,), (csize,))
                ssl = {k: jax.lax.dynamic_slice(v, (lo,), (csize,))
                       for k, v in state.items()}
                pn, sn = rule_apply(psl, g, ssl)
                return (jax.lax.dynamic_update_slice(param, pn, (lo,)),
                        {k: jax.lax.dynamic_update_slice(state[k], sn[k],
                                                         (lo,))
                         for k in state})

            donate = (0, 2) if self.config.donate else ()
            fn = jax.jit(body, donate_argnums=donate)
            self._fused[("chunk",) + key] = fn
        return fn

    def apply_wire_chunk(self, codec, grad_in, lo: int, csize: int,
                         commit: bool = True) -> None:
        """Apply one wire-format *chunk* at element offset ``lo``:
        ``grad_in`` is the chunk's decoded host view (identity codecs)
        or its split wire parts.  ``commit`` bumps the version exactly
        once per op — on the final chunk — so snapshot caches and the
        diff stream keep op-granular version arithmetic."""
        if codec is None or codec.identity:
            payload: Any = jnp.asarray(grad_in)
        else:
            payload = [jnp.asarray(v) for v in grad_in]
        fn = self._fused_chunk_apply(codec, csize)
        self.param, self.rule_state = fn(self.param, payload,
                                         self.rule_state, np.int32(lo))
        if commit:
            self._m_applies.inc()
            self._invalidate()

    def _invalidate(self) -> None:
        self.version += 1
        self._pull_cache = None

    def apply_grad(self, grad) -> None:
        """Apply one device-native gradient (identity wire format): the
        grad is placed with the param's sharding and the donated update
        runs; the old param/state buffers are consumed in place."""
        g = place_flat(grad, self.config)
        self.param, self.rule_state = self._fused_apply()(
            self.param, g, self.rule_state)
        self._m_applies.inc()
        self._invalidate()

    def apply_wire(self, codec, grad_in) -> None:
        """Apply one wire-format gradient: ``grad_in`` is the decoded
        host view (identity codecs) or the codec's split wire parts,
        exactly as the server's legacy path builds them — same math,
        same operand order, so device and host runs stay bitwise equal."""
        if codec.identity:
            self.apply_grad(grad_in)
            return
        parts = [jnp.asarray(v) for v in grad_in]
        self.param, self.rule_state = self._fused_apply(codec)(
            self.param, parts, self.rule_state)
        self._m_applies.inc()
        self._invalidate()

    def seed(self, value) -> None:
        """Whole-shard write (seeding / PARAM_PUSH): re-place, new
        version.  Rule state is deliberately kept — the reference's
        seed overwrites params only.  The placed array is re-owned on
        device (:func:`device_copy`) — a numpy-aliased param entering
        this slot's donated applies would corrupt the heap.  The
        place_flat -> device_copy pairing is a declared owned path
        (`hbm-seed-owned`, MT-D903): dropping the wrapper fails lint."""
        self.param = device_copy(place_flat(value, self.config))
        self._invalidate()

    # -- read path: per-version caches on both sides of the boundary --------

    def snapshot_host(self) -> np.ndarray:
        """This version's device->host copy, cached: N wire reads of one
        committed version cost one d2h however many clients ask.
        `param` is a donated slot (`hbm-snapshot-materialize`,
        MT-D902): the cache must hold the np.asarray materialization,
        never a bare alias the next donated apply would delete."""
        if self._snap_host is None or self._snap_host[0] != self.version:
            self._snap_host = (self.version, np.asarray(self.param))
            self._m_copies.inc()
        return self._snap_host[1]

    def pull_device(self):
        """This version's replicated device array, cached: the device
        analog of the snapshot cache.  Lowered by XLA to an all-gather
        over the shard axis (sharded slots) or a device copy; always a
        fresh buffer, so the donated apply can never delete it out from
        under a holder."""
        cached = self._pull_cache
        if cached is not None and cached[0] == self.version:
            return cached[1]
        if self._replicate is None:
            if self.config.mesh is not None:
                out = NamedSharding(self.config.mesh, PartitionSpec())
                self._replicate = jax.jit(lambda p: p, out_shardings=out)
            else:
                self._replicate = jax.jit(lambda p: p)
        pulled = self._replicate(self.param)
        self._m_gathers.inc()
        self._pull_cache = (self.version, pulled)
        return pulled

    # -- introspection -------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        sharding = getattr(self.param, "sharding", None)
        return {
            "size": self.size,
            "dtype": self.dtype.name,
            "version": self.version,
            "devices": (len(sharding.device_set)
                        if sharding is not None else 1),
            "donate": self.config.donate,
        }
