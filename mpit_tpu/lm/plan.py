"""Shard planning for the LM TrainState — partition rules in, layout out.

Drives the :mod:`mpit_tpu.dplane.partition` engine over the LM's
params+optimizer pytree and lowers the result to the two placement
artifacts the PS stack consumes:

- :meth:`LmPlan.layout` — a **static weighted aligned cut**: one
  contiguous :class:`~mpit_tpu.ps.sharding.Shard` per server, every
  interior boundary on a parameter boundary, targets skewed by
  per-server weights.  Passed to ``ParamClient(layout=...)`` /
  ``ReaderClient(layout=...)`` it replaces the equal split while
  keeping the whole static feature lattice (chunked streaming, int8
  EF, staleness, agg tree) negotiable — the flagship composition.
- :meth:`LmPlan.shard_map` — the same cut lifted into a versioned
  shardctl ShardMap (via :func:`~mpit_tpu.dplane.partition.plan_shard_map`)
  when placement should migrate; per-shard optimizer slots move with
  their shard because the cut never splits a parameter.

Footprint model: a server holding ``S`` f32 elements under rule ``R``
allocates ``(1 + STATE_SLOTS[R]) * 4 * S`` bytes (params + per-element
optimizer slots; scalar step counters are free) — the accounting that
sizes the gang so params+optimizer state exceed one server's
comfortable footprint (docs/WORKLOADS.md).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence

from jax.sharding import PartitionSpec as P

from mpit_tpu.dplane.partition import (
    Segment,
    aligned_cut,
    flat_segments,
    match_report,
    plan_shard_map,
)
from mpit_tpu.optim.rules import state_slots

#: Ordered partition rules for the TinyDecoder TrainState (params AND
#: the mirrored optimizer slots: an opt_state path like
#: ``opt_state/DecoderBlock_0/Dense_0/kernel/m`` contains the same
#: component names, so one table covers both).  First match wins; no
#: catch-all tail — an unmatched non-scalar leaf is a loud error, which
#: is the audit surface tests/test_dplane.py exercises.
PARTITION_RULES = [
    # token + position embeddings: shard the vocab/position axis
    (r"Embed_\d+/embedding", P("mdl", None)),
    # attention qkv/out + MLP kernels: shard the output features
    (r"Dense_\d+/kernel", P(None, "mdl")),
    # biases, norms (and the per-leaf scalar step counters of the
    # optimizer slots resolve as scalars before any rule is consulted)
    (r"Dense_\d+/bias", P()),
    (r"LayerNorm_\d+/(scale|bias)", P()),
]


def audit_rules(tree: Any, rules=None, *, sep: str = "/") -> Dict[str, int]:
    """:func:`match_report` over ``tree`` with a loud failure if any
    non-scalar leaf is unmatched (report value -2).  Returns the report
    so callers can also assert exactly-once coverage."""
    report = match_report(rules if rules is not None else PARTITION_RULES,
                          tree, sep=sep)
    missing = sorted(name for name, idx in report.items() if idx == -2)
    if missing:
        raise ValueError(
            f"{len(missing)} TrainState leaves match no partition rule: "
            f"{missing[:5]}{' ...' if len(missing) > 5 else ''}")
    return report


class LmPlan(NamedTuple):
    """A computed shard plan over one LM param vector."""

    segments: List[Segment]       # ordered leaf extents of the flat vector
    layout: List[Any]             # one Shard per server (weighted cut)
    plong: int                    # flat vector length
    rule: str                     # server-side optimizer rule
    slots: int                    # vector-shaped state arrays per element
    weights: Optional[List[float]]

    def footprint_bytes(self, i: int) -> int:
        """Bytes server ``i`` holds: its f32 shard + optimizer slots."""
        return self.layout[i].size * 4 * (1 + self.slots)

    def shard_map(self, server_ranks: Sequence[int]):
        """The same cut as a version-0 shardctl ShardMap (placement can
        then migrate; slots move with their shard)."""
        from mpit_tpu.shardctl.shardmap import ShardMap

        return ShardMap.from_shards(self.layout, list(server_ranks))

    def summary(self) -> Dict[str, Any]:
        sizes = [s.size for s in self.layout]
        foot = [self.footprint_bytes(i) for i in range(len(self.layout))]
        return {
            "plong": self.plong,
            "segments": len(self.segments),
            "servers": len(self.layout),
            "rule": self.rule,
            "slots": self.slots,
            "shard_elems": sizes,
            "footprint_mb": [round(b / 2**20, 3) for b in foot],
            "total_footprint_mb": round(sum(foot) / 2**20, 3),
            "weights": self.weights,
        }


def plan(params: Any, n_servers: int, *, rule: str = "add",
         server_weights: Optional[Sequence[float]] = None,
         sep: str = "/") -> LmPlan:
    """Cut the raveled ``params`` into ``n_servers`` aligned shards.

    ``server_weights`` (optional) skews the cut targets — a server with
    twice the weight aims at twice the elements, to the nearest
    parameter boundary.  ``rule`` names the server-side optimizer whose
    per-element slot count prices the footprint; it does not change the
    cut (every element of one vector carries the same rule, so equal
    weights already equalize params+slots — weights exist for
    *heterogeneous server budgets*)."""
    if n_servers < 1:
        raise ValueError("need at least one server")
    segments = flat_segments(params, sep=sep)
    plong = segments[-1].end
    weights = ([float(w) for w in server_weights]
               if server_weights is not None else None)
    layout = aligned_cut(plong, segments, n_servers, weights=weights)
    return LmPlan(segments=segments, layout=layout, plong=plong,
                  rule=rule, slots=state_slots(rule), weights=weights)


__all__ = [
    "PARTITION_RULES", "LmPlan", "audit_rules", "plan", "plan_shard_map",
]
