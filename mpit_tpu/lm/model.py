"""Transformer-LM TrainState for the PS stack.

Assembles :class:`mpit_tpu.models.transformer.TinyDecoder` (whose
attention is the ``ops/`` flash kernel on TPU and the jnp reference —
which differentiates without a recompute pass — elsewhere) into the
flat-vector calling convention the parameter server shards: a
:class:`~mpit_tpu.models.flat.FlatModel` plus a next-token NLL over
packed token grids, and the params+optimizer pytree
(:func:`train_state_tree`) that :mod:`mpit_tpu.lm.plan` drives the
partition rules over.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from mpit_tpu.models.flat import FlatModel, flatten_module
from mpit_tpu.models.transformer import TinyDecoder, default_attn


class LmModel(NamedTuple):
    """A built LM: the module, its flat view, and the loss closures."""

    module: Any
    flat: FlatModel
    loss: Callable[..., jnp.ndarray]          # (w, tokens) -> scalar NLL
    value_and_grad: Callable[..., Any]        # (w, tokens) -> (loss, grad)
    seq_len: int
    vocab: int


def _resolve_flash(use_flash: Optional[bool]) -> bool:
    """Default: the pallas kernel on TPU, the jnp reference elsewhere
    (the reference path differentiates without a recompute pass, which
    is the right trade on CPU gangs like the CI smoke)."""
    if use_flash is not None:
        return bool(use_flash)
    return jax.default_backend() == "tpu"


def build(*, vocab: int = 256, d_model: int = 64, n_heads: int = 4,
          n_layers: int = 2, seq_len: int = 128, seed: int = 0,
          use_flash: Optional[bool] = None) -> LmModel:
    """Build the decoder, flatten its params, and close over the
    next-token NLL.  ``max_len`` is pinned to ``seq_len`` — the packed
    stream always fills full sequences, and an exact fit keeps the
    position table out of the sharding slack."""
    module = TinyDecoder(
        vocab=vocab, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        max_len=seq_len,
        attn_fn=default_attn(causal=True, use_flash=_resolve_flash(use_flash)),
    )
    sample = jnp.zeros((1, seq_len), jnp.int32)
    fm = flatten_module(module, jax.random.PRNGKey(seed), sample)

    def loss(w, tokens):
        # tokens: (B, seq_len + 1) int32 — packed, every cell real.
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logp = fm.apply_flat(w, inputs)  # (B, L, V) log-probs
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    return LmModel(module=module, flat=fm, loss=loss,
                   value_and_grad=jax.value_and_grad(loss),
                   seq_len=seq_len, vocab=vocab)


def train_state_tree(params: Any, rule_name: str = "adam") -> Any:
    """The params+optimizer pytree the shard plan is computed over: a
    TrainState-shaped dict whose ``opt_state`` mirrors ``params`` with
    one :mod:`mpit_tpu.optim.rules` state dict per parameter (the
    per-parameter optimizer slots the servers allocate beside their
    shard).  Rule inits share one ``zeros_like`` across their state
    entries (e.g. adam's m and v), so the returned tree contains the
    aliasing that ``hbm.dedupe_state`` exists to break — tests pin that
    the two compose."""
    from mpit_tpu.optim import rules as _rules

    rule = _rules.make(rule_name)
    opt_state = jax.tree_util.tree_map(rule.init, params)
    return {"params": params, "opt_state": opt_state}
