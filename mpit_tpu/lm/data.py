"""Packed token batches — pure functions of ``(seed, step)``.

Sequence packing over the :mod:`mpit_tpu.data.tokens` document stream:
documents are concatenated, EOS-separated, into a flat ``batch *
(seq_len + 1)`` grid and reshaped — no padding, every cell is a real
prediction target.  The ``+ 1`` column lets the trainer slice
``inputs = tokens[:, :-1]`` / ``targets = tokens[:, 1:]`` from one
array.

Determinism contract (tests/test_lm.py pins all three):

- ``packed_batch(seed, step, ...)`` is a pure function — bitwise-equal
  results across calls, processes and machines (the generator is
  counter-keyed Philox; no global RNG state is read or written);
- a :class:`PackedStream` holds no mutable state, so a supervisor
  restart that re-creates the stream and resumes at step ``k`` sees the
  identical batch the dead incarnation would have seen;
- batches for different steps are decorrelated (fresh Philox key per
  step, not an advanced shared stream).
"""

from __future__ import annotations

import numpy as np

from mpit_tpu.data.tokens import VOCAB, doc_batch

#: Separator written between packed documents (byte 0).
EOS = 0


def packed_batch(seed: int, step: int, *, batch: int,
                 seq_len: int) -> np.ndarray:
    """The ``(batch, seq_len + 1)`` int32 token grid of step ``step``.

    Pure: equal arguments => bitwise-identical array, in any process.
    """
    if batch < 1 or seq_len < 2:
        raise ValueError("need batch >= 1 and seq_len >= 2")
    n_cells = batch * (seq_len + 1)
    flat = np.full(n_cells, EOS, np.int32)
    pos = 0
    # doc_batch returns >= n_cells tokens; with one EOS after each
    # document the packed content always fills the grid (the tail
    # document is truncated at the grid edge).
    for doc in doc_batch(seed, step, budget=n_cells):
        if pos >= n_cells:
            break
        take = min(len(doc), n_cells - pos)
        flat[pos:pos + take] = doc[:take]
        pos += take
        if pos < n_cells:
            flat[pos] = EOS  # separator; also a real prediction target
            pos += 1
    return flat.reshape(batch, seq_len + 1)


class PackedStream:
    """Stateless view of the packed stream: ``batch_at(step)`` is
    :func:`packed_batch` with the construction-time shape bound."""

    def __init__(self, seed: int, batch: int, seq_len: int):
        self.seed = int(seed)
        self.batch = int(batch)
        self.seq_len = int(seq_len)
        self.vocab = VOCAB

    def batch_at(self, step: int) -> np.ndarray:
        return packed_batch(self.seed, step, batch=self.batch,
                            seq_len=self.seq_len)
