"""The flagship LM training loop — async PS clients, tokens/sec meter.

Shape mirrors :class:`mpit_tpu.train.trainer.MnistTrainer` (model +
flat params, optimizer dispatch, phase timers) with the MNIST epoch
grid replaced by a step loop over the packed token stream, and the
north-star metric replaced by **tokens/second**:

- every step consumes one ``(batch, seq_len + 1)`` packed grid —
  ``batch * seq_len`` real prediction targets, no padding — so
  ``tokens/sec = batch * seq_len * steps / train_seconds``;
- ``train_seconds`` is the feval phase (local step + blocking PS sync),
  excluding start-up (INIT + seeding), evaluation and teardown — the
  methodology docs/WORKLOADS.md specifies;
- the ``mpit_lm_tokens_total`` counter (plus ``mpit_lm_loss``,
  ``mpit_lm_eval_loss`` and ``mpit_lm_tokens_per_s`` gauges) exposes
  the same quantities to the obs registry for traces and /status.

Evaluation never touches the servers: it runs the jitted loss on a
disjoint stream seed with the worker's current params.  Checkpoint-free
*mid-run* eval against the servers' params is the reader path
(``ReaderClient`` + the same :func:`mpit_tpu.lm.model.build` loss; see
tools/lm_smoke.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.lm.data import PackedStream
from mpit_tpu.lm.model import build
from mpit_tpu.obs import PhaseTimers, get_registry, profiler_trace
from mpit_tpu.optim import EAMSGD, MSGD, Downpour, RuleShell
from mpit_tpu.optim.msgd import MSGDConfig
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger

LM_DEFAULTS = Config(
    # model (vocab is pinned to the byte stream's 256)
    d_model=64,
    n_heads=4,
    n_layers=2,
    seq_len=128,
    use_flash=-1,  # -1 auto (flash on TPU, jnp reference elsewhere); 0/1 pin
    # optimizer (the MnistTrainer knob names, so launch configs carry over)
    opt="downpour",  # sgd|msgd|downpour|eamsgd|easgd|rmsprop|adam|adamax|
    #                  adagrad|adadelta (rule names are server-stateful)
    lr=0.5,
    lrd=0.0,
    lrp=0.0,
    mom=0.0,
    mommax=1.0,
    momdecay=0.0,
    l2wd=0.0,
    mva=0.5,  # eamsgd moving rate
    su=1,     # communication period
    # loop
    steps=200,
    batch=8,
    seed=1,
    eval_every=50,    # 0 disables mid-run eval
    eval_batches=2,
    eval_seed_skew=100_003,  # eval stream seed = seed + skew (disjoint)
    dtype="float32",
    profile_dir="",
)


class LmTrainer:
    KNOWN_OPTS = (
        "sgd", "msgd", "downpour", "eamsgd", "easgd",
        "rmsprop", "adam", "adamax", "adagrad", "adadelta",
    )

    def __init__(self, cfg: Optional[Config] = None, pclient: Any = None,
                 rank: int = 0):
        self.cfg = LM_DEFAULTS.merged(cfg.to_dict() if cfg else None)
        cfg = self.cfg
        self.pc = pclient
        self.rank = rank
        self.log = get_logger("lm", rank)
        self.tm = PhaseTimers()

        use_flash = None if cfg.use_flash < 0 else bool(cfg.use_flash)
        self.model = build(
            d_model=cfg.d_model, n_heads=cfg.n_heads, n_layers=cfg.n_layers,
            seq_len=cfg.seq_len, seed=cfg.seed, use_flash=use_flash,
        )
        dtype = jnp.dtype(cfg.dtype)
        self.w = self.model.flat.w0.astype(dtype)
        self._vgf = self.model.value_and_grad
        self._loss = jax.jit(self.model.loss)

        # Streams: the training stream is per-rank (workers must not
        # mirror each other's batches); eval is a disjoint fixed stream.
        self.stream = PackedStream(cfg.seed + rank, cfg.batch, cfg.seq_len)
        self.eval_stream = PackedStream(cfg.seed + cfg.eval_seed_skew,
                                        cfg.batch, cfg.seq_len)

        _reg = get_registry()
        self._obs = _reg.enabled
        self._m_tokens = _reg.counter("mpit_lm_tokens_total", rank=rank)
        self._m_steps = _reg.counter("mpit_lm_steps_total", rank=rank)
        self._m_loss = _reg.gauge("mpit_lm_loss", rank=rank)
        self._m_eval = _reg.gauge("mpit_lm_eval_loss", rank=rank)
        self._m_tps = _reg.gauge("mpit_lm_tokens_per_s", rank=rank)
        self._optimizer = None  # lazy: eval-only roles never need one

    @property
    def optimizer(self):
        if self._optimizer is None:
            self._optimizer = self._make_optimizer()
        return self._optimizer

    def _make_optimizer(self):
        cfg = self.cfg
        name = cfg.opt
        if name not in self.KNOWN_OPTS:
            raise ValueError(f"unknown optimizer {name!r}; have {self.KNOWN_OPTS}")
        if name in ("sgd", "msgd"):
            mcfg = MSGDConfig(lr=cfg.lr, lrd=cfg.lrd, lrp=cfg.lrp,
                              mom=cfg.mom, mommax=cfg.mommax,
                              momdecay=cfg.momdecay, l2wd=cfg.l2wd)
            return MSGD(mcfg, self._vgf)
        if self.pc is None:
            raise ValueError(
                f"optimizer {name!r} needs a parameter client "
                "(single-process LM runs use sgd/msgd)")
        if name == "downpour":
            return Downpour(self._vgf, self.pc, lr=cfg.lr, lrd=cfg.lrd,
                            l2wd=cfg.l2wd, su=cfg.su)
        if name in ("eamsgd", "easgd"):
            mom = 0.0 if name == "easgd" else cfg.mom
            return EAMSGD(self._vgf, self.pc, lr=cfg.lr, lrd=cfg.lrd,
                          lrp=cfg.lrp, mom=mom, l2wd=cfg.l2wd,
                          mva=cfg.mva, su=cfg.su)
        # Server-stateful rules: the launcher configures the matching
        # server rule; the client ships raw gradients.
        return RuleShell(self._vgf, self.pc, su=cfg.su, mode="global")

    # -- evaluation -----------------------------------------------------------

    def eval_loss(self, w: Optional[jnp.ndarray] = None) -> float:
        """Mean NLL over ``eval_batches`` fixed batches of the disjoint
        eval stream — a pure read of ``w`` (or the live params)."""
        w = self.w if w is None else w
        losses = [
            float(self._loss(w, jnp.asarray(self.eval_stream.batch_at(i))))
            for i in range(max(self.cfg.eval_batches, 1))
        ]
        return float(np.mean(losses))

    # -- the step loop --------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        tokens_per_step = cfg.batch * cfg.seq_len  # real targets per grid
        opt = self.optimizer
        if hasattr(opt, "start"):
            with self.tm.phase("start"):
                self.w = opt.start(self.w)
        history = []
        tokens_total = 0
        train_s = 0.0  # feval incl. blocking sync — the tokens/sec base
        window_losses = []
        with profiler_trace(cfg.get("profile_dir", "")):
            for step in range(cfg.steps):
                tokens = jnp.asarray(self.stream.batch_at(step))
                t0 = time.monotonic()
                with self.tm.phase("feval"):
                    self.w, loss = opt.step(self.w, tokens)
                train_s += time.monotonic() - t0
                tokens_total += tokens_per_step
                window_losses.append(loss)
                self._m_tokens.inc(tokens_per_step)
                self._m_steps.inc()
                last = (step == cfg.steps - 1)
                if cfg.eval_every and (step % cfg.eval_every
                                       == cfg.eval_every - 1 or last):
                    avg_loss = float(jnp.mean(jnp.stack(window_losses)))
                    window_losses = []
                    with self.tm.phase("eval"):
                        ev = self.eval_loss()
                    tps = tokens_total / max(train_s, 1e-9)
                    if self._obs:
                        self._m_loss.set(avg_loss)
                        self._m_eval.set(ev)
                        self._m_tps.set(tps)
                    history.append({"step": step, "avg_loss": avg_loss,
                                    "eval_loss": ev, "tokens_per_s": tps,
                                    "at": self.tm.elapsed()})
                    self.log.info(
                        "step %d avg_loss %.5f eval_loss %.5f tok/s %.0f",
                        step, avg_loss, ev, tps)
        sync_time = getattr(opt, "dusync", 0.0)
        self.tm.add("sync", sync_time)
        # feval net of blocking sync, like MnistTrainer — but tokens/sec
        # keeps the sync in its denominator (a stalled worker earns no
        # throughput credit).
        self.tm.total["feval"] = max(self.tm.total["feval"] - sync_time, 0.0)
        if hasattr(opt, "stop"):
            with self.tm.phase("stop"):
                opt.stop()
        tokens_per_s = tokens_total / max(train_s, 1e-9)
        if self._obs:
            self._m_tps.set(tokens_per_s)
        return {
            "history": history,
            "final_loss": history[-1]["avg_loss"] if history else None,
            "final_eval_loss": history[-1]["eval_loss"] if history else None,
            "tokens_total": tokens_total,
            "tokens_per_s": tokens_per_s,
            "train_seconds": train_s,
            "elapsed": self.tm.elapsed(),
            "timers": dict(self.tm.total),
            "steps": cfg.steps,
        }
