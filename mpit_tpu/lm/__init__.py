"""mpit_tpu.lm — the flagship workload: a sharded transformer LM trained
through the full parameter-server stack, measured in tokens/second.

The subsystem composes machinery that previously had no workload big
enough to be load-bearing simultaneously:

- :mod:`mpit_tpu.lm.model` — transformer-LM TrainState over
  ``models/transformer.TinyDecoder`` + the ``ops/`` attention kernels,
  flattened to the PS wire vector with per-parameter optimizer slots;
- :mod:`mpit_tpu.lm.plan` — ``dplane/partition.py`` rules over the
  params+optimizer pytree, lowered to a weighted **aligned-cut** layout
  sized so params + optimizer state exceed one server's comfortable
  footprint (and to a shardctl ShardMap when placement should migrate);
- :mod:`mpit_tpu.lm.data` — a seeded, bit-reproducible packed token
  stream (same seed => identical batches, in any process);
- :mod:`mpit_tpu.lm.trainer` — the async DOWNPOUR/EAMSGD client loop
  with a ``mpit_lm_tokens_total`` meter; tokens/sec is the headline.

Runbook: docs/WORKLOADS.md.  Launcher entry: ``train/launch.py --lm 1``.
"""

from mpit_tpu.lm.data import EOS, PackedStream, packed_batch
from mpit_tpu.lm.model import LmModel, build, train_state_tree
from mpit_tpu.lm.plan import PARTITION_RULES, LmPlan, audit_rules, plan
from mpit_tpu.lm.trainer import LM_DEFAULTS, LmTrainer

__all__ = [
    "EOS", "PackedStream", "packed_batch",
    "LmModel", "build", "train_state_tree",
    "PARTITION_RULES", "LmPlan", "audit_rules", "plan",
    "LM_DEFAULTS", "LmTrainer",
]
