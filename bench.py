"""Headline benchmark: flagship MNIST EASGD training throughput.

Measures samples/sec of the jitted elastic-averaging train step (the
mlaunch.lua flagship path, reference asyncsgd/mlaunch.lua:39-47 /
optim-eamsgd.lua) on the available accelerator, with parameters and the
elastic center sharded over the device mesh.

``vs_baseline`` compares against a live-measured reference-equivalent:
the same CNN + Nesterov-SGD step in torch on host CPU — the reference
ran its ranks on CPU torch (SURVEY.md §6; the repo publishes no numbers,
BASELINE.md), so CPU-torch throughput of the identical workload is the
honest stand-in.  >1.0 means this framework beats the reference-shaped
run.

Prints exactly one JSON line to stdout.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BATCH = 128
SIDE = 32
WIDTH = 32
WARMUP = 20
ITERS = 500
TORCH_ITERS = 10


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


def bench_jax() -> float:
    import jax
    import jax.numpy as jnp

    from mpit_tpu.data.mnist import load_mnist
    from mpit_tpu.models import MnistCNN, flatten_module
    from mpit_tpu.optim.msgd import MSGDConfig
    from mpit_tpu.parallel import MeshEASGD, make_mesh

    from mpit_tpu.utils.platform import default_devices

    devs = default_devices()
    _log(f"jax devices: {devs}")
    mesh = make_mesh(devs)
    n_dp = mesh.shape["dp"]

    (x_train, y_train, _, _), source = load_mnist(side=SIDE)
    _log(f"data source: {source}")

    module = MnistCNN(side=SIDE, num_classes=10, width=WIDTH)
    x0 = jnp.asarray(x_train[:2], jnp.float32)
    flat = flatten_module(module, jax.random.PRNGKey(0), x0)
    _log(f"flat params: {flat.size}")

    def vgf(w, xb, yb):
        def loss_fn(w):
            logp = flat.apply_flat(w, xb)
            return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

        return jax.value_and_grad(loss_fn)(w)

    # mlaunch flagship config shape: mom=0.99, mva=beta/p, su=100ish; su=1
    # here so the *measured* step includes the elastic exchange every step
    # (worst case for us, most honest vs the async reference).
    trainer = MeshEASGD(
        mesh, vgf, MSGDConfig(lr=1e-2, mom=0.99), mva=0.9 / max(n_dp, 1), su=1
    )
    state = trainer.init(flat.w0)

    n = len(x_train)
    per_worker = BATCH
    need = n_dp * per_worker
    idx = np.arange(need) % n
    xs = jnp.asarray(x_train[idx].reshape(n_dp, per_worker, -1), jnp.float32)
    ys = jnp.asarray(y_train[idx].reshape(n_dp, per_worker), jnp.int32)
    batches = trainer.shard_batch(xs, ys)

    for _ in range(WARMUP):
        state, loss = trainer.step(state, *batches)
    import jax as _j

    _j.block_until_ready(state["w"])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, loss = trainer.step(state, *batches)
    _j.block_until_ready(state["w"])
    dt = time.perf_counter() - t0
    sps = ITERS * n_dp * per_worker / dt
    _log(f"jax: {ITERS} steps x {n_dp} workers x {per_worker} in {dt:.3f}s "
         f"-> {sps:.1f} samples/s (loss {float(loss.mean()):.4f})")
    return sps


def bench_torch_cpu() -> float:
    """Reference-equivalent: identical CNN + Nesterov SGD, torch on CPU."""
    import torch
    import torch.nn as tnn

    torch.manual_seed(0)
    torch.set_num_threads(max(torch.get_num_threads(), 1))
    model = tnn.Sequential(
        tnn.Conv2d(1, WIDTH, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Conv2d(WIDTH, 2 * WIDTH, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Flatten(),
        tnn.Linear((SIDE // 4) ** 2 * 2 * WIDTH, 4 * WIDTH), tnn.ReLU(),
        tnn.Linear(4 * WIDTH, 10), tnn.LogSoftmax(dim=1),
    )
    opt = torch.optim.SGD(model.parameters(), lr=1e-2, momentum=0.99, nesterov=True)
    lossf = tnn.NLLLoss()
    x = torch.randn(BATCH, 1, SIDE, SIDE)
    y = torch.randint(0, 10, (BATCH,))

    def step():
        opt.zero_grad()
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()

    for _ in range(3):
        step()
    t0 = time.perf_counter()
    for _ in range(TORCH_ITERS):
        step()
    dt = time.perf_counter() - t0
    sps = TORCH_ITERS * BATCH / dt
    _log(f"torch-cpu: {TORCH_ITERS} steps of {BATCH} in {dt:.3f}s -> {sps:.1f} samples/s")
    return sps


def main():
    sps = bench_jax()
    try:
        base = bench_torch_cpu()
        vs = sps / base if base > 0 else 0.0
    except Exception as e:  # torch missing/broken: report raw throughput
        _log(f"torch baseline failed: {e!r}")
        vs = 0.0
    print(json.dumps({
        "metric": "mnist_easgd_train_samples_per_sec",
        "value": round(sps, 1),
        "unit": "samples/s",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
