"""Headline benchmark: the BASELINE north-star, measured on real training.

Three metrics in one JSON line (reference shapes: asyncsgd/goot.lua:144-157
time-to-test-error loop, asyncsgd/ptest.lua:58-67 push/pull MB/s):

- ``value`` / ``metric`` — steady-state training throughput (samples/s)
  of the flagship MNIST EASGD mesh trainer (mlaunch.lua:39-47 path).
  Each epoch is a fresh shuffle staged to HBM in one transfer (the
  framework's device_stream input pipeline); every step trains a
  different batch; timing is the latency-cancelled fetch-fenced recipe
  of :mod:`mpit_tpu.utils.timing` over whole epoch passes.
- ``time_to_target_s`` — wall-clock from post-compile t0 until test
  error <= ``target_test_err`` (compile is AOT/warmed and reported
  separately as ``compile_s``).  Default mode is ``device_loop``: the
  entire train-to-target runs as one ``lax.while_loop`` device program,
  so the number measures the device rather than per-epoch tunnel RTTs
  (on-chip A/B in docs/NORTHSTAR_r5.md).  ``data_source`` names what
  was trained on — this environment has no real MNIST; the loader uses
  the committed optdigits fixture (data/mnist.py docstring).
- ``ps_pushpull_mbs_per_chip`` — bi-directional PS shard push/pull
  bandwidth per chip over the mesh ``shard`` axis (the ptest.lua
  measurement riding ICI collectives instead of MPI).

``vs_baseline`` compares throughput against a live-measured
reference-equivalent: the same CNN + Nesterov-SGD step in torch on host
CPU with the same staged-epoch input pipeline (one permuted tensor per
epoch, per-step slices) — the reference ran its ranks on CPU torch
(SURVEY.md §6) and publishes no absolute numbers (BASELINE.md), so
CPU-torch throughput of the identical workload is the honest stand-in.
>1.0 means this framework beats the reference-shaped run.

Reproducibility (round-4 discipline): every leg runs
``MPIT_BENCH_REPS`` times (default 3) and the JSON carries the median
plus the per-run values and max-min spread — the tunnel jitter is
documented at ±20% (docs/KERNEL_BENCH.md), so a single-shot number is
not evidence.  jit compile is excluded from the timed region (the
trainers precompile with the persistent XLA cache,
utils/platform.enable_compile_cache) and reported separately as
``compile_s``; ``time_to_target_s`` is wall clock from t0 *after*
warmup, as a warm-cache user would experience it.

Env knobs: MPIT_BENCH_EPOCHS (default 30), MPIT_BENCH_MB (PS payload,
default 640 — the reference ptest.lua:3 scale), MPIT_BENCH_ROUNDS
(default 20), MPIT_BENCH_REPS (default 3).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# stdout carries exactly one JSON line (the driver contract); all
# framework logging goes to stderr.
os.environ.setdefault("MPIT_LOG_STREAM", "stderr")

BATCH = 128
SIDE = 32
EPOCHS = int(os.environ.get("MPIT_BENCH_EPOCHS", "30"))
PS_MB = float(os.environ.get("MPIT_BENCH_MB", "640"))  # ptest.lua:3 payload
PS_ROUNDS = int(os.environ.get("MPIT_BENCH_ROUNDS", "20"))
REPS = max(int(os.environ.get("MPIT_BENCH_REPS", "3")), 1)
TORCH_ITERS = 30


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


def _median(xs):
    return float(np.median(np.asarray(xs, np.float64)))


def _spread_pct(xs):
    """max-min spread as % of the median (0 for degenerate medians)."""
    med = _median(xs)
    return abs(max(xs) - min(xs)) / abs(med) * 100.0 if med else 0.0


def _torch_threads() -> int:
    """Cores actually usable by this process (affinity/cgroup aware) —
    os.cpu_count() would oversubscribe a pinned container and slow the
    torch baseline below its honest rate."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # non-linux
        return os.cpu_count() or 1


def bench_train() -> dict:
    """Flagship mesh-EASGD run to target test error on the real stream."""
    from mpit_tpu.train.mesh_launch import (
        FLAGSHIP_BENCH_KWARGS, MESH_LAUNCH_DEFAULTS, run,
    )

    # target_test_err: BASELINE's north star is 1% on real MNIST; this
    # environment has only the sklearn-digits fallback, where the flagship
    # config plateaus at ~2.2% (it memorizes the 1527-example train split)
    # — 2% is the achievable stand-in, and the JSON names both the target
    # and the source.
    target = float(os.environ.get("MPIT_BENCH_TARGET", "0.02"))
    # device_loop=1: the whole train-to-target runs as ONE lax.while_loop
    # device program (on-device shuffle + epoch scan + eval + early
    # exit), so time_to_target measures the device, not the tunnel RTT —
    # flipped after the on-chip A/B measured 1.0 s vs 4.3 s median for
    # the host epoch loop on this exact config (benchmarks/
    # device_loop_ab.py, docs/NORTHSTAR_r5.md).  The steady-throughput
    # leg is mode-independent (same compiled epoch scan either way).
    # MPIT_BENCH_DEVICE_LOOP=0 restores the host-loop measurement.
    device_loop = int(os.environ.get("MPIT_BENCH_DEVICE_LOOP", "1"))
    cfg = MESH_LAUNCH_DEFAULTS.merged(
        **FLAGSHIP_BENCH_KWARGS, epochs=EPOCHS,
        target_test_err=target, stop_at_target=1, measure_throughput=1,
        device_loop=device_loop,
    )
    result = run(cfg)
    result["target_test_err"] = target
    result["train_mode"] = "device_loop" if device_loop else "host_loop"
    err = result["final_test_err"]
    _log(
        f"train: {result['samples_trained']} samples in "
        f"{result['train_time']:.2f}s wall train-time "
        f"({result['samples_per_sec']} samples/s wall, "
        f"{result['samples_per_sec_steady']} steady); final test_err "
        f"{'n/a' if err is None else format(err, '.4f')}; time_to_target "
        f"{result['time_to_target']}; source {result['data_source']}"
    )
    return result


def bench_ps_pushpull() -> dict:
    """ptest.lua analog: PS shard push/pull bandwidth over ICI (shared
    implementation: :func:`mpit_tpu.parallel.collective.measure_ps_pushpull`)."""
    from mpit_tpu.parallel.collective import measure_ps_pushpull

    r = measure_ps_pushpull(PS_MB, rounds=PS_ROUNDS)
    _log(f"ps: {r['ms_per_round']:.2f} ms/round of {r['payload_mb']:.1f} MB "
         f"-> {r['mbs']:.1f} MB/s ({r['per_chip']:.1f} MB/s/chip, "
         f"{r['devices']} chips)")
    return r


def bench_torch_cpu() -> float:
    """Reference-equivalent: identical CNN + Nesterov SGD, torch on CPU,
    same staged-epoch pipeline as the jax leg (one permuted tensor per
    epoch, per-step slices of fresh data).  Threads pinned to the host's
    core count (deterministic per host — the round-3 725->1157 samples/s
    drift came from an unpinned, load-dependent thread pool)."""
    import torch
    import torch.nn as tnn

    from mpit_tpu.data.mnist import load_mnist
    from mpit_tpu.train.mesh_launch import FLAGSHIP_BENCH_KWARGS

    # The torch leg must mirror the jax leg's workload shape exactly —
    # raise, not assert: python -O would compile an assert away and the
    # torch leg would silently time a different workload.
    if (FLAGSHIP_BENCH_KWARGS["batch"] != BATCH
            or FLAGSHIP_BENCH_KWARGS["side"] != SIDE):
        raise ValueError(
            "torch baseline shape drifted from FLAGSHIP_BENCH_KWARGS: "
            f"batch {FLAGSHIP_BENCH_KWARGS['batch']} vs {BATCH}, "
            f"side {FLAGSHIP_BENCH_KWARGS['side']} vs {SIDE}")

    (x_train, y_train, _, _), _src = load_mnist(side=SIDE)
    torch.manual_seed(0)
    torch.set_num_threads(_torch_threads())
    width = 32
    model = tnn.Sequential(
        tnn.Conv2d(1, width, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Conv2d(width, 2 * width, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Flatten(),
        tnn.Linear((SIDE // 4) ** 2 * 2 * width, 4 * width), tnn.ReLU(),
        tnn.Linear(4 * width, 10), tnn.LogSoftmax(dim=1),
    )
    opt = torch.optim.SGD(model.parameters(), lr=1e-2, momentum=0.99, nesterov=True)
    lossf = tnn.NLLLoss()
    n = len(x_train)
    rng = np.random.default_rng(0)
    steps = max(n // BATCH, 1)
    order = rng.permutation(n)[: steps * BATCH]
    x_ep = torch.from_numpy(
        x_train[order].reshape(steps, BATCH, 1, SIDE, SIDE))
    y_ep = torch.from_numpy(
        y_train[order].astype(np.int64).reshape(steps, BATCH))

    def step(i):
        opt.zero_grad()
        loss = lossf(model(x_ep[i % steps]), y_ep[i % steps])
        loss.backward()
        opt.step()

    for i in range(3):
        step(i)
    t0 = time.perf_counter()
    for i in range(TORCH_ITERS):
        step(i)
    dt = time.perf_counter() - t0
    sps = TORCH_ITERS * BATCH / dt
    _log(f"torch-cpu: {TORCH_ITERS} staged steps of {BATCH} in {dt:.3f}s "
         f"-> {sps:.1f} samples/s")
    return sps


def _device_responsive(timeout_s: float = 240.0) -> bool:
    """Probe the accelerator in a SUBPROCESS with a hard timeout: the
    axon tunnel has been observed to wedge outright (a cached trivial
    jit never returns), and a hung bench leaves the driver with no
    record at all — an explicit failure line beats silence."""
    import subprocess

    code = (
        "import jax, jax.numpy as jnp;"
        "print(float(jax.jit(lambda a: (a @ a).sum())"
        "(jnp.ones((256, 256)))))"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    if r.returncode != 0:
        # A fast crash is NOT a hang: surface the real traceback and
        # let the bench proceed to fail with it rather than fabricating
        # a tunnel-outage diagnosis.
        _log("device probe crashed (not a hang):")
        _log(r.stderr.decode(errors="replace")[-2000:])
    return True


def _probe_retries() -> int:
    return max(int(os.environ.get("MPIT_BENCH_PROBE_RETRIES", "3")), 1)


def _device_responsive_with_retry() -> bool:
    """Bounded probe-retry: tunnel outages are often transient (observed
    wedges clear within minutes to hours), and a single failed probe
    erased round 4's entire evidence record — so retry a few times over
    ~15 min before giving up (MPIT_BENCH_PROBE_RETRIES=1 restores the
    single-shot behavior for interactive runs)."""
    retries = _probe_retries()
    wait_s = float(os.environ.get("MPIT_BENCH_PROBE_WAIT", "420"))
    for attempt in range(1, retries + 1):
        if _device_responsive():
            return True
        _log(f"device probe {attempt}/{retries} timed out: "
             "accelerator/tunnel unresponsive")
        if attempt < retries:
            _log(f"retrying in {wait_s:.0f}s")
            time.sleep(wait_s)
    return False


def _outage_record() -> dict:
    return {
        "metric": "mnist_easgd_train_samples_per_sec",
        "value": None, "unit": "samples/s", "vs_baseline": None,
        "error": "device unresponsive: a trivial jitted matmul never "
                 "completed within a 240s probe (tunnel outage; "
                 f"probed {_probe_retries()} times before giving up)",
    }


def _cpu_fallback() -> int:
    """The accelerator is wedged: capture the whole bench on the CPU
    backend in a child process (JAX_PLATFORMS=cpu) and emit that record
    tagged ``"backend": "cpu"`` — a degraded-but-real measurement.
    Rounds 4 and 5 (BENCH_r04/05.json) emitted ``value: null`` on tunnel
    outages and lost their perf evidence entirely; a CPU capture keeps
    the record comparable run-over-run.  Returns the exit code."""
    import subprocess

    _log("device unresponsive: falling back to a JAX_PLATFORMS=cpu capture")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MPIT_BENCH_PROBE_RETRIES="1")
    timeout = float(os.environ.get("MPIT_BENCH_CPU_TIMEOUT", "5400"))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        for stream in (e.stdout, e.stderr):
            if stream:
                sys.stderr.write(stream if isinstance(stream, str)
                                 else stream.decode(errors="replace"))
        _log(f"cpu fallback capture timed out after {timeout:.0f}s")
        print(json.dumps(_outage_record()))
        return 1
    sys.stderr.write(out.stderr)
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    if out.returncode != 0 or not lines:
        _log(f"cpu fallback capture failed rc={out.returncode}")
        print(json.dumps(_outage_record()))
        return 1
    rec = json.loads(lines[-1])
    rec["backend"] = "cpu"
    rec["fallback"] = ("accelerator unresponsive after probe retries; "
                       "JAX_PLATFORMS=cpu capture")
    print(json.dumps(rec))
    return 0


def main():
    if not _device_responsive_with_retry():
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # Already the fallback backend (or an explicit CPU run) —
            # nothing further to degrade to.
            print(json.dumps(_outage_record()))
            sys.exit(1)
        sys.exit(_cpu_fallback())
    trains = []
    for rep in range(REPS):
        _log(f"-- train rep {rep + 1}/{REPS} --")
        trains.append(bench_train())
    sps_runs = [
        t["samples_per_sec_steady"] or t["samples_per_sec"] or 0.0
        for t in trains
    ]
    ttt_runs = [t["time_to_target"] for t in trains
                if t["time_to_target"] is not None]
    compile_runs = [t["compile_s"] for t in trains
                    if t["compile_s"] is not None]
    sps = _median(sps_runs)
    train = trains[0]  # target/data_source/final_err are rep-invariant

    ps_runs = []
    for rep in range(REPS):
        try:
            ps_runs.append(bench_ps_pushpull())
        except Exception as e:
            _log(f"ps bandwidth rep {rep + 1} failed: {e!r}")
    ps_chip = [r["per_chip"] for r in ps_runs if r.get("per_chip")]

    torch_runs = []
    for rep in range(REPS):
        try:
            torch_runs.append(bench_torch_cpu())
        except Exception as e:  # torch missing/broken: report raw throughput
            _log(f"torch baseline rep {rep + 1} failed: {e!r}")
    base = _median(torch_runs) if torch_runs else 0.0
    vs = sps / base if base > 0 else 0.0

    import jax

    print(json.dumps({
        "metric": "mnist_easgd_train_samples_per_sec",
        "backend": jax.default_backend(),
        "value": round(sps, 1),
        "unit": "samples/s",
        "vs_baseline": round(vs, 3),
        "reps": REPS,
        "value_runs": [round(v, 1) for v in sps_runs],
        "value_spread_pct": round(_spread_pct(sps_runs), 1),
        "time_to_target_s": round(_median(ttt_runs), 3) if ttt_runs else None,
        "time_to_target_runs": [round(v, 3) for v in ttt_runs],
        "compile_s": round(_median(compile_runs), 3) if compile_runs else None,
        "target_test_err": train["target_test_err"],
        "train_mode": train["train_mode"],
        "measurement_condition": "BASELINE.md §'Measurement condition in "
        "THIS environment' (optdigits-8x8 fixture, 2% target; no-egress "
        "environment, real MNIST unavailable)",
        "final_test_err": train["final_test_err"],
        "epochs_run": len(train["history"]),
        "data_source": train["data_source"],
        "ps_pushpull_mbs_per_chip": round(_median(ps_chip), 1)
        if ps_chip else None,
        "ps_pushpull_runs": [round(v, 1) for v in ps_chip],
        "ps_spread_pct": round(_spread_pct(ps_chip), 1) if ps_chip else None,
        "ps_devices": ps_runs[0]["devices"] if ps_runs else 0,
        "torch_cpu_sps": round(base, 1) if torch_runs else None,
        "torch_cpu_runs": [round(v, 1) for v in torch_runs],
        "torch_threads": _torch_threads(),
    }))


if __name__ == "__main__":
    main()
