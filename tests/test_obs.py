"""mpit_tpu.obs — metrics registry, op spans, Chrome-trace export.

Three layers of assertion:

1. the registry/recorder primitives (bucketing math, exposition format,
   the null-object disabled path — including a microbenchmark proving
   "disabled" really is a no-op object, not a branch tree);
2. deterministic counters: under a seeded every-k fault plan the
   retry/dedup/drop counters on both ends must match the arithmetic of
   the plan *exactly* (computed by replaying ``FaultPlan.decide``, not
   eyeballed), and a trace export round-trips through the validator;
3. attribution: a dropped-then-retried op is findable in the exported
   trace with its [epoch, seq] identity and retry count.

Obs global state is process-wide, so every test that enables it goes
through the ``obs_on`` fixture (enable + reset, restore after).
"""

import json
import threading
import time

import numpy as np
import pytest

from mpit_tpu import obs
from mpit_tpu.aio import Scheduler, aio_sleep
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import FaultPlan, FaultyTransport, FTConfig
from mpit_tpu.obs import metrics as obs_metrics
from mpit_tpu.obs import spans as obs_spans
from mpit_tpu.obs import trace as obs_trace
from mpit_tpu.ps import ParamClient, ParamServer, tags

DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})

#: fast retry posture for LocalRouter-speed gangs (mirrors test_ft.py)
FAST_FT = FTConfig(op_deadline_s=0.25, max_retries=8,
                   backoff_base_s=0.005, backoff_cap_s=0.02)


@pytest.fixture
def obs_on():
    obs.configure(enabled=True, reset=True)
    try:
        yield obs.get_registry()
    finally:
        obs.configure(enabled=None, reset=True)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# registry primitives


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = obs_metrics.Registry()
        c = reg.counter("mpit_x_total", rank=1)
        c.inc()
        c.inc(4)
        assert c.value == 5
        assert reg.counter("mpit_x_total", rank=1) is c  # get-or-create
        assert reg.counter("mpit_x_total", rank=2) is not c
        g = reg.gauge("mpit_depth")
        g.set(7)
        g.add(-2)
        assert g.value == 5
        h = reg.histogram("mpit_h_seconds")
        for v in (0.75, 1.5, 3.0):
            h.observe(v)
        assert h.count == 3 and h.vmax == 3.0 and h.vmin == 0.75

    def test_log2_bucketing_is_exact(self):
        # [2^(e-1), 2^e) lands in the bucket whose key is e.
        assert obs_metrics.bucket_index(0.75) == \
            0 - obs_metrics.HIST_LO_EXP  # (0.5, 1.0) -> exponent 0
        assert obs_metrics.bucket_index(1.0) == 1 - obs_metrics.HIST_LO_EXP
        assert obs_metrics.bucket_index(0.0) == 0
        assert obs_metrics.bucket_index(-5.0) == 0
        assert obs_metrics.bucket_index(float(2 ** 40)) == \
            obs_metrics.HIST_BUCKETS - 1  # clamped top
        h = obs_metrics.Histogram("h")
        h.observe(0.75)
        snap = h.snapshot()
        assert snap["buckets"] == {0: 1}

    def test_kind_collision_fails_loudly(self):
        reg = obs_metrics.Registry()
        reg.counter("mpit_k")
        with pytest.raises(TypeError, match="already registered"):
            reg.histogram("mpit_k")

    def test_snapshot_and_exposition(self):
        reg = obs_metrics.Registry()
        reg.counter("mpit_c_total", peer=3).inc(2)
        reg.histogram("mpit_h").observe(1.5)
        snap = reg.snapshot()
        assert snap['mpit_c_total{peer="3"}'] == 2
        assert snap["mpit_h"]["count"] == 1
        text = reg.exposition()
        assert 'mpit_c_total{peer="3"} 2' in text
        assert "mpit_h_count 1" in text
        assert 'le="+Inf"' in text
        assert "mpit_c_total" in reg.format_summary(prefix="mpit_c")
        assert "mpit_h" not in reg.format_summary(prefix="mpit_c")

    def test_timer_context_observes(self):
        reg = obs_metrics.Registry()
        with reg.timer("mpit_t_seconds", codec="int8"):
            pass
        h = reg.histogram("mpit_t_seconds", codec="int8")
        assert h.count == 1 and h.total >= 0.0

    def test_counter_incs_are_thread_safe_enough(self):
        reg = obs_metrics.Registry()
        c = reg.counter("mpit_mt_total")
        threads = [threading.Thread(
            target=lambda: [c.inc() for _ in range(10000)])
            for _ in range(4)]
        for t in threads:
            t.start()
        join_all(threads)
        assert c.value == 40000


class TestDisabledPath:
    def test_disabled_registry_is_the_null_object(self):
        assert not obs.obs_enabled()
        reg = obs.get_registry()
        assert reg is obs_metrics.NULL_REGISTRY
        assert reg.counter("x") is obs_metrics.NULL
        assert reg.histogram("y", a=1) is obs_metrics.NULL
        assert reg.timer("z") is obs_metrics.NULL
        rec = obs_spans.get_recorder()
        assert rec is obs_spans.NULL_RECORDER
        assert rec.op("GRAD", peer=1) is obs_spans.NULL_SPAN
        assert rec.task_begin("t") is None
        # nothing accumulates anywhere
        obs_metrics.NULL.inc(10)
        obs_metrics.NULL.observe(1.0)
        assert obs_metrics.NULL.value == 0
        assert reg.snapshot() == {} and reg.exposition() == ""

    def test_disabled_path_microbenchmark(self):
        """The no-op-object claim, measured: 200k disabled counter incs
        plus 20k disabled op-span lifecycles must finish far inside a
        generous absolute budget (>= 5 µs/op would still pass — real
        cost is tens of ns).  Catches anyone replacing the null object
        with env reads or clock calls per operation."""
        reg = obs.get_registry()
        c = reg.counter("mpit_bench_total")
        rec = obs_spans.get_recorder()
        t0 = time.perf_counter()
        for _ in range(200_000):
            c.inc()
        for _ in range(20_000):
            sp = rec.op("GRAD", peer=1, side="client")
            sp.mark("encode")
            sp.end("ok")
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.1, (
            f"disabled-path overhead {elapsed:.3f}s for 220k ops — the "
            "null objects are no longer no-ops")

    def test_configure_flips_and_restores(self):
        obs.configure(enabled=True, reset=True)
        try:
            assert obs.obs_enabled()
            assert obs.get_registry() is not obs_metrics.NULL_REGISTRY
            assert obs_spans.get_recorder().enabled
        finally:
            obs.configure(enabled=None, reset=True)
        assert not obs.obs_enabled()

    def test_registry_or_local_always_counts(self):
        reg = obs.registry_or_local()
        assert reg.enabled
        c = reg.counter("mpit_local_total")
        c.inc()
        assert c.value == 1


# ---------------------------------------------------------------------------
# spans + trace export


class TestSpans:
    def test_op_span_records_phases_and_histogram(self, obs_on):
        rec = obs_spans.get_recorder()
        sp = rec.op("GRAD", peer=3, side="client", epoch=0)
        sp.mark("encode")
        sp.mark("send")
        sp.note(seq=7)
        sp.end("ok", retries=1)
        sp.end("ignored")  # idempotent
        assert len(rec.spans) == 1
        done = rec.spans[0]
        assert done.outcome == "ok"
        assert done.args["seq"] == 7 and done.args["retries"] == 1
        assert [p for p, _ in done.marks] == ["encode", "send"]
        h = obs_on.histogram("mpit_ps_op_seconds", op="GRAD", side="client")
        assert h.count == 1

    def test_scheduler_records_task_lifecycles(self, obs_on):
        sched = Scheduler(idle_usec=0)
        sched.spawn(aio_sleep(0.01), name="nap")
        sched.wait()
        rec = obs_spans.get_recorder()
        names = [name for name, _, _, state in rec.tasks]
        assert "nap" in names
        assert obs_on.counter("mpit_aio_steps_total").value > 0
        assert obs_on.counter("mpit_aio_tasks_total").value >= 1


class TestTraceExport:
    def test_round_trip_and_balance(self, obs_on, tmp_path):
        rec = obs_spans.get_recorder()
        for i in range(3):
            sp = rec.op("GRAD", peer=0, side="client", epoch=0, seq=i + 1)
            sp.mark("send")
            sp.end("ok")
        tok = rec.task_begin("svc")
        rec.task_end(tok, "svc", "DONE")
        path = obs_trace.write_rank_trace(str(tmp_path / "t.json"), 7,
                                          role="client")
        stats = obs_trace.validate_trace(path)
        assert stats["ops"] == 3 and stats["tasks"] == 1
        obj = json.load(open(path))
        assert obj["otherData"]["ranks"]["7"]["role"] == "client"
        # merged file validates too and keeps the pid
        merged = str(tmp_path / "m.json")
        obs_trace.merge_traces(merged, [path])
        assert obs_trace.validate_trace(merged)["pids"] == 1

    def test_validator_rejects_malformed(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [
            {"ph": "E", "name": "GRAD", "pid": 0, "tid": 1, "ts": 1.0}]}))
        with pytest.raises(ValueError, match="no open B"):
            obs_trace.validate_trace(str(bad))
        bad.write_text(json.dumps({"traceEvents": [
            {"ph": "B", "name": "GRAD", "pid": 0, "tid": 1, "ts": 1.0}]}))
        with pytest.raises(ValueError, match="unclosed"):
            obs_trace.validate_trace(str(bad))
        bad.write_text(json.dumps({"nope": 1}))
        with pytest.raises(ValueError, match="traceEvents"):
            obs_trace.validate_trace(str(bad))

    def test_cli_entry(self, obs_on, tmp_path, capsys):
        rec = obs_spans.get_recorder()
        sp = rec.op("PARAM", peer=0)
        sp.end("ok")
        path = obs_trace.write_rank_trace(str(tmp_path / "t.json"), 0)
        assert obs_trace.main([path]) == 0
        assert obs_trace.main([str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------------------------------
# back-compat: the utils/timers fold


class TestTimersFold:
    def test_utils_reexports_are_the_obs_objects(self):
        from mpit_tpu import utils
        from mpit_tpu.obs import timers as obs_timers
        from mpit_tpu.utils import timers as utils_timers

        assert utils_timers.PhaseTimers is obs_timers.PhaseTimers
        assert utils.trace_annotation is obs_timers.trace_annotation
        assert utils_timers.profiler_trace is obs_timers.profiler_trace
        assert obs.PhaseTimers is obs_timers.PhaseTimers

    def test_phase_timers_still_work(self):
        tm = obs.PhaseTimers()
        with tm.phase("feval"):
            pass
        assert tm.count["feval"] == 1


# ---------------------------------------------------------------------------
# deterministic counters under seeded fault plans (2s/2c gang)


def launch_gang(nservers, nclients, client_plans=None,
                client_ft=FAST_FT, server_ft=None):
    """FT PS topology over LocalRouter with FaultyTransport client seams
    (the test_ft.py harness shape, trimmed to what these tests need)."""
    n = nservers + nclients
    router = LocalRouter(n)
    sranks, cranks = list(range(nservers)), list(range(nservers, n))
    server_ft = server_ft or FTConfig(rejoin=True)
    servers, threads = [], []
    for r in sranks:
        servers.append(ParamServer(r, cranks, router.endpoint(r), rule="add",
                                   ft=server_ft))
        threads.append(threading.Thread(target=servers[-1].start, daemon=True))
    for t in threads:
        t.start()
    clients, transports = [], []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        transports.append(ep)
        clients.append(ParamClient(r, sranks, ep,
                                   seed_servers=(r == cranks[0]),
                                   ft=client_ft))
    return servers, clients, threads, transports


def run_gang(servers, clients, threads, rounds, size=64):
    rng = np.random.default_rng(7)
    starters = []
    params = []
    for c in clients:
        p = (rng.normal(size=size).astype(np.float32)
             if not params else np.zeros(size, np.float32))
        params.append(p)
        starters.append(threading.Thread(
            target=c.start, args=(p, np.zeros(size, np.float32)),
            daemon=True))
    for t in starters:
        t.start()
    join_all(starters)
    for r in range(rounds):
        for c in clients:
            c.grad[:] = rng.normal(size=size).astype(np.float32)
            c.async_send_grad()
            c.wait()
    for c in clients:
        c.stop()
    join_all(threads)


def simulate_grad_channel(plan, src, dst, rounds):
    """Replay the plan's arithmetic for one (client -> server) GRAD
    channel under the retry protocol: a dropped data frame times out and
    is resent (the resend advances the per-channel count), a passed or
    duplicated frame is acked.  Returns (sends, drops, dups)."""
    sends = drops = dups = 0
    n = 0
    for _ in range(rounds):
        while True:
            n += 1
            sends += 1
            verdict = plan.decide(src, dst, tags.GRAD, n)
            if verdict == "drop":
                drops += 1
                continue  # deadline fires, client resends
            if verdict == "dup":
                dups += 1
            break  # delivered (possibly twice) -> acked
    return sends, drops, dups


class TestDeterministicCounters:
    def test_drop_plan_counters_match_plan_arithmetic(self):
        """Every-3rd GRAD dropped on each client->server channel: the
        transport drop counters, the client retry counters and the
        server dedup counters must equal the replayed plan arithmetic
        exactly — not approximately."""
        rounds, nservers, nclients = 6, 2, 2
        plans = {i: FaultPlan(seed=i, drop_every=3,
                              tags=frozenset({tags.GRAD}))
                 for i in range(nclients)}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans)
        run_gang(servers, clients, threads, rounds)
        for i, (c, tr) in enumerate(zip(clients, transports)):
            want_drops = want_retries = 0
            for dst in range(nservers):
                _, drops, dups = simulate_grad_channel(
                    plans[i], c.rank, dst, rounds)
                assert dups == 0
                want_drops += drops
                # every dropped GRAD costs exactly one resend
                want_retries += drops
            assert tr.dropped == want_drops
            assert c.retries == want_retries
            assert want_drops > 0  # the plan actually fired
        # drops never reach the server: no dups, no stale, all applied
        assert sum(s.dup_ops for s in servers) == 0
        assert sum(s.stale_drops for s in servers) == 0
        # one GRAD per (client, server) pair per round (sharded vector)
        assert (sum(s.grads_applied for s in servers)
                == rounds * nclients * nservers)

    def test_dup_plan_counters_match_plan_arithmetic(self):
        """Every-2nd data frame duplicated: the server's dup counter
        must equal the transports' duplication counters exactly (each
        injected duplicate is admitted DUP and re-acked), with zero
        retries — duplication never stalls the op."""
        rounds, nservers, nclients = 5, 2, 2
        plans = {i: FaultPlan(seed=i, dup_every=2, tags=DATA_TAGS)
                 for i in range(nclients)}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans)
        run_gang(servers, clients, threads, rounds)
        injected = sum(tr.duplicated for tr in transports)
        assert injected > 0
        assert sum(s.dup_ops for s in servers) == injected
        assert sum(c.retries for c in clients) == 0
        assert (sum(s.grads_applied for s in servers)
                == rounds * nclients * nservers)

    def test_fault_plan_env_spec_drives_the_same_counters(self, monkeypatch):
        """The env-spec path (MPIT_FT_FAULT_PLAN) parses to the same
        plan object the direct tests use — the deterministic-counter
        contract holds for env-configured gangs too."""
        monkeypatch.setenv("MPIT_FT_FAULT_PLAN",
                           f"seed=0,drop_every=3,tags={tags.GRAD}")
        plan = FaultPlan.from_env()
        assert plan == FaultPlan(seed=0, drop_every=3,
                                 tags=frozenset({tags.GRAD}))


# ---------------------------------------------------------------------------
# the acceptance scenario: fault-injected gang -> attributable trace


class TestFaultTraceAttribution:
    def test_dropped_then_retried_op_is_attributable(self, obs_on, tmp_path):
        """2s/2c gang under an every-k drop plan with obs enabled: the
        exported Chrome trace must contain the retried GRAD op's span
        with its [epoch, seq] identity and retry count, the trace must
        validate (balanced B/E), and the drop/retry/dup counters must
        match the plan arithmetic."""
        rounds, nservers, nclients = 4, 2, 2
        plans = {0: FaultPlan(seed=0, drop_every=2,
                              tags=frozenset({tags.GRAD}))}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans)
        run_gang(servers, clients, threads, rounds)
        # counters match the plan arithmetic on both ends
        want_drops = want_retries = 0
        for dst in range(nservers):
            _, drops, _ = simulate_grad_channel(
                plans[0], clients[0].rank, dst, rounds)
            want_drops += drops
            want_retries += drops
        assert transports[0].dropped == want_drops > 0
        assert clients[0].retries == want_retries
        assert sum(s.dup_ops for s in servers) == 0  # drops, not dups
        # export + validate
        path = obs_trace.write_rank_trace(
            str(tmp_path / "trace.json"), rank=clients[0].rank, role="worker")
        stats = obs_trace.validate_trace(path)
        assert stats["ops"] > 0
        # the retried op is attributable: a GRAD span with retries >= 1
        # carrying its [epoch, seq] identity and per-attempt phases
        obj = json.load(open(path))
        begins = {}
        retried = None
        for ev in obj["traceEvents"]:
            if ev["ph"] == "B" and ev["name"] == "GRAD":
                begins[(ev["tid"], ev["ts"])] = ev
                if ev["args"].get("retries", 0) >= 1:
                    retried = ev
        assert retried is not None, "no retried GRAD span in the trace"
        assert retried["args"]["epoch"] == 0
        assert retried["args"]["seq"] >= 1
        assert retried["args"]["peer"] in range(nservers)
        # its phase events exist on the same tid, including the backoff
        phases = {ev["name"] for ev in obj["traceEvents"]
                  if ev["ph"] == "X" and ev["tid"] == retried["tid"]}
        assert "GRAD.backoff" in phases and "GRAD.send" in phases
        # server-side spans recorded the applies (same process here, so
        # the shared recorder holds both sides)
        server_grads = [sp for sp in obs_spans.get_recorder().spans
                        if sp.name == "GRAD"
                        and sp.args.get("side") == "server"]
        assert (sum(1 for sp in server_grads if sp.outcome == "applied")
                == rounds * nclients * nservers)


# ---------------------------------------------------------------------------
# process-gang smoke: per-rank parts merged by the launcher (slow)


@pytest.mark.slow
def test_gang_merges_rank_traces(tmp_path, monkeypatch):
    """np=3 process gang with MPIT_OBS_TRACE: every child writes a part,
    the parent merges them, the merged trace validates and carries one
    pid per rank plus per-rank metrics riders."""
    from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

    trace_path = str(tmp_path / "gang_trace.json")
    monkeypatch.setenv("MPIT_OBS_TRACE", trace_path)
    cfg = LAUNCH_DEFAULTS.merged(
        np=3, opt="downpour", epochs=1, model="linear", side=8,
        batch=64, master_freq=2, device_policy="cpu",
    )
    results = launch_processes(cfg, timeout=600)
    assert set(results) == {0, 1, 2}
    stats = obs_trace.validate_trace(trace_path)
    assert stats["pids"] == 3 and stats["events"] > 0
    obj = json.load(open(trace_path))
    ranks = obj["otherData"]["ranks"]
    assert set(ranks) == {"0", "1", "2"}
    server_metrics = ranks["0"]["metrics"]
    assert any(k.startswith("mpit_ps_grads_applied_total")
               for k in server_metrics)
    assert not list(tmp_path.glob("gang_trace.json.rank*")), \
        "part files should be cleaned up after the merge"
