"""mpit_tpu.obs — metrics registry, op spans, Chrome-trace export.

Three layers of assertion:

1. the registry/recorder primitives (bucketing math, exposition format,
   the null-object disabled path — including a microbenchmark proving
   "disabled" really is a no-op object, not a branch tree);
2. deterministic counters: under a seeded every-k fault plan the
   retry/dedup/drop counters on both ends must match the arithmetic of
   the plan *exactly* (computed by replaying ``FaultPlan.decide``, not
   eyeballed), and a trace export round-trips through the validator;
3. attribution: a dropped-then-retried op is findable in the exported
   trace with its [epoch, seq] identity and retry count.

Obs global state is process-wide, so every test that enables it goes
through the ``obs_on`` fixture (enable + reset, restore after).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from mpit_tpu import obs
from mpit_tpu.aio import EXEC, Scheduler, aio_sleep
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import FaultPlan, FaultyTransport, FTConfig, RetryExhausted
from mpit_tpu.obs import flight as obs_flight
from mpit_tpu.obs import metrics as obs_metrics
from mpit_tpu.obs import profile as obs_profile
from mpit_tpu.obs import spans as obs_spans
from mpit_tpu.obs import statusd as obs_statusd
from mpit_tpu.obs import top as obs_top
from mpit_tpu.obs import trace as obs_trace
from mpit_tpu.obs.__main__ import main as obs_cli
from mpit_tpu.ps import ParamClient, ParamServer, tags

DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})

#: fast retry posture for LocalRouter-speed gangs (mirrors test_ft.py)
FAST_FT = FTConfig(op_deadline_s=0.25, max_retries=8,
                   backoff_base_s=0.005, backoff_cap_s=0.02)


@pytest.fixture
def obs_on():
    obs.configure(enabled=True, reset=True)
    try:
        yield obs.get_registry()
    finally:
        obs.configure(enabled=None, reset=True)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# registry primitives


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = obs_metrics.Registry()
        c = reg.counter("mpit_x_total", rank=1)
        c.inc()
        c.inc(4)
        assert c.value == 5
        assert reg.counter("mpit_x_total", rank=1) is c  # get-or-create
        assert reg.counter("mpit_x_total", rank=2) is not c
        g = reg.gauge("mpit_depth")
        g.set(7)
        g.add(-2)
        assert g.value == 5
        h = reg.histogram("mpit_h_seconds")
        for v in (0.75, 1.5, 3.0):
            h.observe(v)
        assert h.count == 3 and h.vmax == 3.0 and h.vmin == 0.75

    def test_log2_bucketing_is_exact(self):
        # [2^(e-1), 2^e) lands in the bucket whose key is e.
        assert obs_metrics.bucket_index(0.75) == \
            0 - obs_metrics.HIST_LO_EXP  # (0.5, 1.0) -> exponent 0
        assert obs_metrics.bucket_index(1.0) == 1 - obs_metrics.HIST_LO_EXP
        assert obs_metrics.bucket_index(0.0) == 0
        assert obs_metrics.bucket_index(-5.0) == 0
        assert obs_metrics.bucket_index(float(2 ** 40)) == \
            obs_metrics.HIST_BUCKETS - 1  # clamped top
        h = obs_metrics.Histogram("h")
        h.observe(0.75)
        snap = h.snapshot()
        assert snap["buckets"] == {0: 1}

    def test_kind_collision_fails_loudly(self):
        reg = obs_metrics.Registry()
        reg.counter("mpit_k")
        with pytest.raises(TypeError, match="already registered"):
            reg.histogram("mpit_k")

    def test_snapshot_and_exposition(self):
        reg = obs_metrics.Registry()
        reg.counter("mpit_c_total", peer=3).inc(2)
        reg.histogram("mpit_h").observe(1.5)
        snap = reg.snapshot()
        assert snap['mpit_c_total{peer="3"}'] == 2
        assert snap["mpit_h"]["count"] == 1
        text = reg.exposition()
        assert 'mpit_c_total{peer="3"} 2' in text
        assert "mpit_h_count 1" in text
        assert 'le="+Inf"' in text
        assert "mpit_c_total" in reg.format_summary(prefix="mpit_c")
        assert "mpit_h" not in reg.format_summary(prefix="mpit_c")

    def test_timer_context_observes(self):
        reg = obs_metrics.Registry()
        with reg.timer("mpit_t_seconds", codec="int8"):
            pass
        h = reg.histogram("mpit_t_seconds", codec="int8")
        assert h.count == 1 and h.total >= 0.0

    def test_counter_incs_are_thread_safe_enough(self):
        reg = obs_metrics.Registry()
        c = reg.counter("mpit_mt_total")
        threads = [threading.Thread(
            target=lambda: [c.inc() for _ in range(10000)])
            for _ in range(4)]
        for t in threads:
            t.start()
        join_all(threads)
        assert c.value == 40000


class TestDisabledPath:
    def test_disabled_registry_is_the_null_object(self):
        assert not obs.obs_enabled()
        reg = obs.get_registry()
        assert reg is obs_metrics.NULL_REGISTRY
        assert reg.counter("x") is obs_metrics.NULL
        assert reg.histogram("y", a=1) is obs_metrics.NULL
        assert reg.timer("z") is obs_metrics.NULL
        rec = obs_spans.get_recorder()
        assert rec is obs_spans.NULL_RECORDER
        assert rec.op("GRAD", peer=1) is obs_spans.NULL_SPAN
        assert rec.task_begin("t") is None
        assert rec.open_ops() == []
        # the flight recorder is the shared null object too
        fl = obs_flight.get_flight()
        assert fl is obs_flight.NULL_FLIGHT
        fl.record("op", name="GRAD")
        assert fl.dump("anything") is None and fl.events == ()
        # the CPU profiler is the shared null object too: no clock
        # reads, no samples, nothing to snapshot
        prof = obs_profile.get_profiler()
        assert prof is obs_profile.NULL_PROFILER
        assert not prof.enabled
        assert prof.cpu_now() == 0.0
        prof.step("t", 0.5)
        prof.sample(3)
        assert prof.samples == () and prof.cpu_seconds == 0.0
        assert prof.top_tasks() == []
        # and no statusd endpoint (no socket) without MPIT_OBS_HTTP
        assert obs_statusd.maybe_start(0) is None
        # nothing accumulates anywhere
        obs_metrics.NULL.inc(10)
        obs_metrics.NULL.observe(1.0)
        assert obs_metrics.NULL.value == 0
        assert reg.snapshot() == {} and reg.exposition() == ""

    def test_disabled_path_microbenchmark(self):
        """The no-op-object claim, measured: 200k disabled counter incs
        plus 20k disabled op-span lifecycles plus 20k disabled
        flight-recorder records plus 20k disabled profiler step/sample
        pairs must finish far inside a generous absolute budget
        (>= 5 µs/op would still pass — real cost is tens of ns).
        Catches anyone replacing the null objects — the registry's,
        the span recorder's, the flight recorder's, or the CPU
        profiler's — with env reads or clock calls per operation."""
        reg = obs.get_registry()
        c = reg.counter("mpit_bench_total")
        rec = obs_spans.get_recorder()
        fl = obs_flight.get_flight()
        prof = obs_profile.get_profiler()
        t0 = time.perf_counter()
        for _ in range(200_000):
            c.inc()
        for _ in range(20_000):
            sp = rec.op("GRAD", peer=1, side="client")
            sp.mark("encode")
            sp.end("ok")
        for _ in range(20_000):
            fl.record("op", name="GRAD", outcome="ok")
        for _ in range(20_000):
            prof.step("t", prof.cpu_now())
            prof.sample(0)
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.2, (
            f"disabled-path overhead {elapsed:.3f}s for 260k ops — the "
            "null objects are no longer no-ops")

    def test_configure_flips_and_restores(self):
        obs.configure(enabled=True, reset=True)
        try:
            assert obs.obs_enabled()
            assert obs.get_registry() is not obs_metrics.NULL_REGISTRY
            assert obs_spans.get_recorder().enabled
        finally:
            obs.configure(enabled=None, reset=True)
        assert not obs.obs_enabled()

    def test_registry_or_local_always_counts(self):
        reg = obs.registry_or_local()
        assert reg.enabled
        c = reg.counter("mpit_local_total")
        c.inc()
        assert c.value == 1


# ---------------------------------------------------------------------------
# spans + trace export


class TestSpans:
    def test_op_span_records_phases_and_histogram(self, obs_on):
        rec = obs_spans.get_recorder()
        sp = rec.op("GRAD", peer=3, side="client", epoch=0)
        sp.mark("encode")
        sp.mark("send")
        sp.note(seq=7)
        sp.end("ok", retries=1)
        sp.end("ignored")  # idempotent
        assert len(rec.spans) == 1
        done = rec.spans[0]
        assert done.outcome == "ok"
        assert done.args["seq"] == 7 and done.args["retries"] == 1
        assert [p for p, _ in done.marks] == ["encode", "send"]
        h = obs_on.histogram("mpit_ps_op_seconds", op="GRAD", side="client")
        assert h.count == 1

    def test_scheduler_records_task_lifecycles(self, obs_on):
        sched = Scheduler(idle_usec=0)
        sched.spawn(aio_sleep(0.01), name="nap")
        sched.wait()
        rec = obs_spans.get_recorder()
        names = [name for name, _, _, state, _cpu in rec.tasks]
        assert "nap" in names
        assert obs_on.counter("mpit_aio_steps_total").value > 0
        assert obs_on.counter("mpit_aio_tasks_total").value >= 1


class TestTraceExport:
    def test_round_trip_and_balance(self, obs_on, tmp_path):
        rec = obs_spans.get_recorder()
        for i in range(3):
            sp = rec.op("GRAD", peer=0, side="client", epoch=0, seq=i + 1)
            sp.mark("send")
            sp.end("ok")
        tok = rec.task_begin("svc")
        rec.task_end(tok, "svc", "DONE")
        path = obs_trace.write_rank_trace(str(tmp_path / "t.json"), 7,
                                          role="client")
        stats = obs_trace.validate_trace(path)
        assert stats["ops"] == 3 and stats["tasks"] == 1
        obj = json.load(open(path))
        assert obj["otherData"]["ranks"]["7"]["role"] == "client"
        # merged file validates too and keeps the pid
        merged = str(tmp_path / "m.json")
        obs_trace.merge_traces(merged, [path])
        assert obs_trace.validate_trace(merged)["pids"] == 1

    def test_validator_rejects_malformed(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [
            {"ph": "E", "name": "GRAD", "pid": 0, "tid": 1, "ts": 1.0}]}))
        with pytest.raises(ValueError, match="no open B"):
            obs_trace.validate_trace(str(bad))
        bad.write_text(json.dumps({"traceEvents": [
            {"ph": "B", "name": "GRAD", "pid": 0, "tid": 1, "ts": 1.0}]}))
        with pytest.raises(ValueError, match="unclosed"):
            obs_trace.validate_trace(str(bad))
        bad.write_text(json.dumps({"nope": 1}))
        with pytest.raises(ValueError, match="traceEvents"):
            obs_trace.validate_trace(str(bad))

    def test_cli_entry(self, obs_on, tmp_path, capsys):
        rec = obs_spans.get_recorder()
        sp = rec.op("PARAM", peer=0)
        sp.end("ok")
        path = obs_trace.write_rank_trace(str(tmp_path / "t.json"), 0)
        assert obs_trace.main([path]) == 0
        assert obs_trace.main([str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------------------------------
# back-compat: the utils/timers fold


class TestTimersFold:
    def test_utils_reexports_are_the_obs_objects(self):
        from mpit_tpu import utils
        from mpit_tpu.obs import timers as obs_timers
        from mpit_tpu.utils import timers as utils_timers

        assert utils_timers.PhaseTimers is obs_timers.PhaseTimers
        assert utils.trace_annotation is obs_timers.trace_annotation
        assert utils_timers.profiler_trace is obs_timers.profiler_trace
        assert obs.PhaseTimers is obs_timers.PhaseTimers

    def test_phase_timers_still_work(self):
        tm = obs.PhaseTimers()
        with tm.phase("feval"):
            pass
        assert tm.count["feval"] == 1


# ---------------------------------------------------------------------------
# deterministic counters under seeded fault plans (2s/2c gang)


def launch_gang(nservers, nclients, client_plans=None,
                client_ft=FAST_FT, server_ft=None):
    """FT PS topology over LocalRouter with FaultyTransport client seams
    (the test_ft.py harness shape, trimmed to what these tests need)."""
    n = nservers + nclients
    router = LocalRouter(n)
    sranks, cranks = list(range(nservers)), list(range(nservers, n))
    server_ft = server_ft or FTConfig(rejoin=True)
    servers, threads = [], []
    for r in sranks:
        servers.append(ParamServer(r, cranks, router.endpoint(r), rule="add",
                                   ft=server_ft))
        threads.append(threading.Thread(target=servers[-1].start, daemon=True))
    for t in threads:
        t.start()
    clients, transports = [], []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        transports.append(ep)
        clients.append(ParamClient(r, sranks, ep,
                                   seed_servers=(r == cranks[0]),
                                   ft=client_ft))
    return servers, clients, threads, transports


def run_gang(servers, clients, threads, rounds, size=64):
    rng = np.random.default_rng(7)
    starters = []
    params = []
    for c in clients:
        p = (rng.normal(size=size).astype(np.float32)
             if not params else np.zeros(size, np.float32))
        params.append(p)
        starters.append(threading.Thread(
            target=c.start, args=(p, np.zeros(size, np.float32)),
            daemon=True))
    for t in starters:
        t.start()
    join_all(starters)
    for r in range(rounds):
        for c in clients:
            c.grad[:] = rng.normal(size=size).astype(np.float32)
            c.async_send_grad()
            c.wait()
    for c in clients:
        c.stop()
    join_all(threads)


def simulate_grad_channel(plan, src, dst, rounds):
    """Replay the plan's arithmetic for one (client -> server) GRAD
    channel under the retry protocol: a dropped data frame times out and
    is resent (the resend advances the per-channel count), a passed or
    duplicated frame is acked.  Returns (sends, drops, dups)."""
    sends = drops = dups = 0
    n = 0
    for _ in range(rounds):
        while True:
            n += 1
            sends += 1
            verdict = plan.decide(src, dst, tags.GRAD, n)
            if verdict == "drop":
                drops += 1
                continue  # deadline fires, client resends
            if verdict == "dup":
                dups += 1
            break  # delivered (possibly twice) -> acked
    return sends, drops, dups


class TestDeterministicCounters:
    def test_drop_plan_counters_match_plan_arithmetic(self):
        """Every-3rd GRAD dropped on each client->server channel: the
        transport drop counters, the client retry counters and the
        server dedup counters must equal the replayed plan arithmetic
        exactly — not approximately."""
        rounds, nservers, nclients = 6, 2, 2
        plans = {i: FaultPlan(seed=i, drop_every=3,
                              tags=frozenset({tags.GRAD}))
                 for i in range(nclients)}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans)
        run_gang(servers, clients, threads, rounds)
        for i, (c, tr) in enumerate(zip(clients, transports)):
            want_drops = want_retries = 0
            for dst in range(nservers):
                _, drops, dups = simulate_grad_channel(
                    plans[i], c.rank, dst, rounds)
                assert dups == 0
                want_drops += drops
                # every dropped GRAD costs exactly one resend
                want_retries += drops
            assert tr.dropped == want_drops
            assert c.retries == want_retries
            assert want_drops > 0  # the plan actually fired
        # drops never reach the server: no dups, no stale, all applied
        assert sum(s.dup_ops for s in servers) == 0
        assert sum(s.stale_drops for s in servers) == 0
        # one GRAD per (client, server) pair per round (sharded vector)
        assert (sum(s.grads_applied for s in servers)
                == rounds * nclients * nservers)

    def test_dup_plan_counters_match_plan_arithmetic(self):
        """Every-2nd data frame duplicated: the server's dup counter
        must equal the transports' duplication counters exactly (each
        injected duplicate is admitted DUP and re-acked), with zero
        retries — duplication never stalls the op."""
        rounds, nservers, nclients = 5, 2, 2
        plans = {i: FaultPlan(seed=i, dup_every=2, tags=DATA_TAGS)
                 for i in range(nclients)}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans)
        run_gang(servers, clients, threads, rounds)
        injected = sum(tr.duplicated for tr in transports)
        assert injected > 0
        assert sum(s.dup_ops for s in servers) == injected
        assert sum(c.retries for c in clients) == 0
        assert (sum(s.grads_applied for s in servers)
                == rounds * nclients * nservers)

    def test_fault_plan_env_spec_drives_the_same_counters(self, monkeypatch):
        """The env-spec path (MPIT_FT_FAULT_PLAN) parses to the same
        plan object the direct tests use — the deterministic-counter
        contract holds for env-configured gangs too."""
        monkeypatch.setenv("MPIT_FT_FAULT_PLAN",
                           f"seed=0,drop_every=3,tags={tags.GRAD}")
        plan = FaultPlan.from_env()
        assert plan == FaultPlan(seed=0, drop_every=3,
                                 tags=frozenset({tags.GRAD}))


# ---------------------------------------------------------------------------
# the acceptance scenario: fault-injected gang -> attributable trace


class TestFaultTraceAttribution:
    def test_dropped_then_retried_op_is_attributable(self, obs_on, tmp_path):
        """2s/2c gang under an every-k drop plan with obs enabled: the
        exported Chrome trace must contain the retried GRAD op's span
        with its [epoch, seq] identity and retry count, the trace must
        validate (balanced B/E), and the drop/retry/dup counters must
        match the plan arithmetic."""
        rounds, nservers, nclients = 4, 2, 2
        plans = {0: FaultPlan(seed=0, drop_every=2,
                              tags=frozenset({tags.GRAD}))}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans)
        run_gang(servers, clients, threads, rounds)
        # counters match the plan arithmetic on both ends
        want_drops = want_retries = 0
        for dst in range(nservers):
            _, drops, _ = simulate_grad_channel(
                plans[0], clients[0].rank, dst, rounds)
            want_drops += drops
            want_retries += drops
        assert transports[0].dropped == want_drops > 0
        assert clients[0].retries == want_retries
        assert sum(s.dup_ops for s in servers) == 0  # drops, not dups
        # export + validate
        path = obs_trace.write_rank_trace(
            str(tmp_path / "trace.json"), rank=clients[0].rank, role="worker")
        stats = obs_trace.validate_trace(path)
        assert stats["ops"] > 0
        # the retried op is attributable: a GRAD span with retries >= 1
        # carrying its [epoch, seq] identity and per-attempt phases
        obj = json.load(open(path))
        begins = {}
        retried = None
        for ev in obj["traceEvents"]:
            if ev["ph"] == "B" and ev["name"] == "GRAD":
                begins[(ev["tid"], ev["ts"])] = ev
                if ev["args"].get("retries", 0) >= 1:
                    retried = ev
        assert retried is not None, "no retried GRAD span in the trace"
        assert retried["args"]["epoch"] == 0
        assert retried["args"]["seq"] >= 1
        assert retried["args"]["peer"] in range(nservers)
        # its phase events exist on the same tid, including the backoff
        phases = {ev["name"] for ev in obj["traceEvents"]
                  if ev["ph"] == "X" and ev["tid"] == retried["tid"]}
        assert "GRAD.backoff" in phases and "GRAD.send" in phases
        # server-side spans recorded the applies (same process here, so
        # the shared recorder holds both sides)
        server_grads = [sp for sp in obs_spans.get_recorder().spans
                        if sp.name == "GRAD"
                        and sp.args.get("side") == "server"]
        assert (sum(1 for sp in server_grads if sp.outcome == "applied")
                == rounds * nclients * nservers)


# ---------------------------------------------------------------------------
# gradient staleness: deterministic counts under a sequential schedule

#: staleness-tracking retry posture (FAST_FT + the header extension)
STALE_FT = FTConfig(op_deadline_s=0.25, max_retries=8,
                    backoff_base_s=0.005, backoff_cap_s=0.02,
                    staleness=True)


def run_sequential(servers, clients, threads, rounds, size=64):
    """Drive every round from ONE thread in a fixed interleave — all
    clients read, then all clients write, in client order — so the
    server-side apply order (and with it every staleness value) is a
    pure function of (nservers, nclients, rounds), replayable exactly.
    Starts stay threaded (the INIT rendezvous needs every client
    announcing before phase 2)."""
    rng = np.random.default_rng(7)
    starters, params = [], []
    for c in clients:
        p = (rng.normal(size=size).astype(np.float32)
             if not params else np.zeros(size, np.float32))
        params.append(p)
        starters.append(threading.Thread(
            target=c.start, args=(p, np.zeros(size, np.float32)),
            daemon=True))
    for t in starters:
        t.start()
    join_all(starters)
    for _ in range(rounds):
        for c in clients:
            c.async_recv_param()
            c.wait()
        for c in clients:
            c.grad[:] = rng.normal(size=size).astype(np.float32)
            c.async_send_grad()
            c.wait()
    for c in clients:
        c.stop()
    join_all(threads)


def replay_staleness(nservers, nclients, rounds):
    """The sequential schedule's staleness arithmetic: version starts at
    1 per server (the seed push), every applied grad bumps it, and each
    client's basis is the version at its read.  Returns
    {(client_idx, server_rank): {staleness_value: count}}."""
    version = [1] * nservers
    basis = [[0] * nservers for _ in range(nclients)]
    out = {}
    for _ in range(rounds):
        for ci in range(nclients):
            for s in range(nservers):
                basis[ci][s] = version[s]
        for ci in range(nclients):
            for s in range(nservers):
                stal = version[s] - basis[ci][s]
                pair = out.setdefault((ci, s), {})
                pair[stal] = pair.get(stal, 0) + 1
                version[s] += 1
    return out


def expected_bucket_dict(values):
    """{staleness_value: n} -> the exact Histogram.snapshot() buckets."""
    out = {}
    for v, n in values.items():
        key = obs_metrics.bucket_index(float(v)) + obs_metrics.HIST_LO_EXP
        out[key] = out.get(key, 0) + n
    return out


class TestStalenessDeterministic:
    def _assert_exact(self, obs_on, servers, clients, rounds,
                      nservers, nclients):
        want = replay_staleness(nservers, nclients, rounds)
        for (ci, s), values in want.items():
            hist = obs_on.histogram("mpit_ps_grad_staleness",
                                    rank=s, client=clients[ci].rank)
            snap = hist.snapshot()
            assert snap["count"] == sum(values.values()), (ci, s, snap)
            assert snap["sum"] == float(sum(v * n
                                            for v, n in values.items()))
            assert snap["buckets"] == expected_bucket_dict(values), \
                (ci, s, snap["buckets"])

    def test_fault_free_counts_match_replay_exactly(self, obs_on):
        """2s/2c, sequential schedule: client 0's grads land at
        staleness 0, client 1's at 1 (client 0's apply intervenes
        between its read and its write) — bucket-exact."""
        rounds, nservers, nclients = 5, 2, 2
        servers, clients, threads, _ = launch_gang(
            nservers, nclients, client_ft=STALE_FT)
        run_sequential(servers, clients, threads, rounds)
        self._assert_exact(obs_on, servers, clients, rounds,
                           nservers, nclients)

    def test_drop_plan_staleness_and_retries_match_replay(self, obs_on):
        """Every-2nd GRAD dropped on client 0: the retry machinery must
        be *invisible* to staleness — the op applies exactly once at the
        same schedule position — while the retry counters match the
        replayed plan arithmetic.  Both exact, same run."""
        rounds, nservers, nclients = 4, 2, 2
        plans = {0: FaultPlan(seed=0, drop_every=2,
                              tags=frozenset({tags.GRAD}))}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans, client_ft=STALE_FT)
        run_sequential(servers, clients, threads, rounds)
        self._assert_exact(obs_on, servers, clients, rounds,
                           nservers, nclients)
        want_drops = want_retries = 0
        for dst in range(nservers):
            _, drops, _ = simulate_grad_channel(
                plans[0], clients[0].rank, dst, rounds)
            want_drops += drops
            want_retries += drops
        assert transports[0].dropped == want_drops > 0
        assert clients[0].retries == want_retries
        assert sum(s.dup_ops for s in servers) == 0

    def test_delay_plan_staleness_matches_replay(self, obs_on):
        """Every-2nd GRAD delayed (inside the deadline): delivery order
        per channel is preserved, nothing retries, and the staleness
        histogram still equals the replay exactly."""
        rounds, nservers, nclients = 4, 2, 2
        plans = {i: FaultPlan(seed=i, delay_every=2, delay_polls=3,
                              tags=frozenset({tags.GRAD}))
                 for i in range(nclients)}
        servers, clients, threads, transports = launch_gang(
            nservers, nclients, client_plans=plans, client_ft=STALE_FT)
        run_sequential(servers, clients, threads, rounds)
        self._assert_exact(obs_on, servers, clients, rounds,
                           nservers, nclients)
        assert sum(tr.delayed for tr in transports) > 0
        assert sum(c.retries for c in clients) == 0

    def test_legacy_init_negotiates_extension_off(self, obs_on):
        """Mixed gang: a staleness-tracking framed client and a plain
        legacy (v1 INIT) client on one server.  The extension must be
        per pair — 24-byte headers for the tracker, the byte-identical
        16/0-byte legacy wire for the other — and only the tracker
        grows a staleness histogram."""
        rounds, nservers = 2, 2
        n = nservers + 2
        router = LocalRouter(n)
        sranks, cranks = list(range(nservers)), list(range(nservers, n))
        servers, threads = [], []
        for r in sranks:
            servers.append(ParamServer(r, cranks, router.endpoint(r),
                                       rule="add", ft=FTConfig(rejoin=True)))
            threads.append(threading.Thread(target=servers[-1].start,
                                            daemon=True))
        for t in threads:
            t.start()
        clients = [
            ParamClient(cranks[0], sranks, router.endpoint(cranks[0]),
                        seed_servers=True, ft=STALE_FT),
            ParamClient(cranks[1], sranks, router.endpoint(cranks[1]),
                        seed_servers=False, ft=FTConfig()),  # legacy v1
        ]
        assert clients[0]._stale and clients[0]._hdr == 24
        assert not clients[1]._stale and clients[1]._hdr == 0
        run_sequential(servers, clients, threads, rounds)
        for s in servers:
            assert s._stale_track[cranks[0]] is True
            assert s._stale_track.get(cranks[1], False) is False
        assert (sum(s.grads_applied for s in servers)
                == rounds * 2 * nservers)
        stale_keys = [k for k in obs_on.snapshot()
                      if k.startswith("mpit_ps_grad_staleness")]
        assert stale_keys  # the tracker produced histograms...
        assert all(f'client="{cranks[0]}"' in k for k in stale_keys), \
            stale_keys  # ...and the legacy client none

    def test_staleness_without_framing_is_inert(self):
        """FTConfig(staleness=True) with no op deadline: nothing to
        extend — the client keeps the headerless legacy wire."""
        cfg = FTConfig(staleness=True)
        assert not cfg.stale_track
        router = LocalRouter(2)
        client = ParamClient(1, [0], router.endpoint(1), ft=cfg)
        assert not client._stale and client._hdr == 0


# ---------------------------------------------------------------------------
# statusd: the live introspection endpoint


def _http_get(port, route):
    import urllib.error

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=5) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


class TestStatusd:
    def test_endpoints_serve_metrics_status_trace(self, obs_on):
        obs_on.counter("mpit_bench_total", rank=7).inc(3)
        rec = obs_spans.get_recorder()
        done = rec.op("PARAM", peer=0, side="client", epoch=0, seq=4)
        done.end("ok")
        open_span = rec.op("GRAD", peer=1, side="client", epoch=0, seq=5)
        open_span.mark("send")
        obs.register_status_provider("probe", lambda: {"hello": 1})
        srv = obs_statusd.StatusServer(0, rank=3, role="worker")
        try:
            code, body = _http_get(srv.port, "/metrics")
            assert code == 200
            assert 'mpit_bench_total{rank="7"} 3' in body.decode()
            code, body = _http_get(srv.port, "/status")
            status = json.loads(body)
            assert (status["rank"], status["role"]) == (3, "worker")
            assert status["probe"] == {"hello": 1}
            inflight = status["inflight_ops"]
            assert len(inflight) == 1 and inflight[0]["op"] == "GRAD"
            assert inflight[0]["seq"] == 5
            assert inflight[0]["phase"] == "send"
            assert inflight[0]["elapsed_s"] >= 0
            code, body = _http_get(srv.port, "/trace")
            stats = obs_trace.validate_trace(json.loads(body))
            assert stats["ops"] == 1  # the finished span; open ones wait
            code, _ = _http_get(srv.port, "/nope")
            assert code == 404
        finally:
            srv.close()
            open_span.end("ok")

    def test_maybe_start_env_gating(self, obs_on, monkeypatch):
        monkeypatch.delenv("MPIT_OBS_HTTP", raising=False)
        assert obs_statusd.maybe_start(0) is None
        monkeypatch.setenv("MPIT_OBS_HTTP", "0")  # port 0 = OS-assigned
        srv = obs_statusd.maybe_start(0, role="server")
        try:
            assert srv is not None and srv.port > 0
            _, body = _http_get(srv.port, "/status")
            assert json.loads(body)["role"] == "server"
        finally:
            srv.close()

    def test_provider_failure_is_contained(self, obs_on):
        def boom():
            raise RuntimeError("provider died")

        obs.register_status_provider("boom", boom)
        srv = obs_statusd.StatusServer(0, rank=1)
        try:
            code, body = _http_get(srv.port, "/status")
            assert code == 200
            assert "provider died" in json.loads(body)["boom"]["error"]
        finally:
            srv.close()

    def test_roles_register_providers_when_obs_on(self, obs_on):
        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0), rule="add")
        client = ParamClient(1, [0], router.endpoint(1))
        section = obs_statusd._PROVIDERS["server0"]()
        assert section["role"] == "server"
        assert section["clients"]["1"]["state"] == "active"
        section = obs_statusd._PROVIDERS["client1"]()
        assert section["role"] == "client" and section["rank"] == 1
        assert server is not None and client is not None


# ---------------------------------------------------------------------------
# flight recorder: ring, dumps, failure-path triggers


class TestFlightRecorder:
    def test_ring_is_bounded_and_dump_validates(self, obs_on, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        fl = obs_flight.get_flight()
        fl.set_identity(rank=5, role="worker")
        for i in range(obs_flight.CAPACITY + 40):
            fl.record("op", name="GRAD", seq=i)
        assert len(fl.events) == obs_flight.CAPACITY  # bounded ring
        path = fl.dump("unit_test", tasks=[("recv_grad:1.g0", "EXEC")],
                       note="hello")
        assert path and str(tmp_path) in path
        stats = obs_flight.validate_dump(path)
        assert stats["reason"] == "unit_test" and stats["rank"] == 5
        assert stats["events"] == obs_flight.CAPACITY
        assert stats["tasks"] == 1
        # CLI validation agrees
        assert obs_cli(["flight", path]) == 0
        # a second dump never overwrites the first
        path2 = fl.dump("unit_test")
        assert path2 != path

    def test_validator_rejects_malformed(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"schema": "nope"}))
        with pytest.raises(ValueError, match="schema"):
            obs_flight.validate_dump(str(bad))
        bad.write_text(json.dumps({
            "schema": "mpit_flight/1", "reason": "x", "pid": 1,
            "wall_time": 1.0, "events": [{"kind": "op"}], "metrics": {}}))
        with pytest.raises(ValueError, match="numeric t"):
            obs_flight.validate_dump(str(bad))
        assert obs_cli(["flight", str(bad)]) == 1

    def test_retry_exhausted_dumps_flight(self, obs_on, tmp_path,
                                          monkeypatch):
        """A severed server makes the client's GRAD exhaust its retries:
        the raise must leave a validated flight dump on disk carrying
        the retry_exhausted event and the live task table."""
        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        fast = FTConfig(op_deadline_s=0.05, max_retries=1,
                        backoff_base_s=0.005, backoff_cap_s=0.01)
        plans = {0: FaultPlan(sever_after=0)}  # every send dropped
        servers, clients, threads, _ = launch_gang(
            1, 1, client_plans=plans, client_ft=fast)
        client = clients[0]
        with pytest.raises(Exception) as exc_info:
            client.start(np.zeros(8, np.float32), np.zeros(8, np.float32))
        assert isinstance(
            getattr(exc_info.value, "cause", exc_info.value),
            RetryExhausted)
        for role in clients + servers:
            role.live.stop()
        join_all(threads)
        dumps = sorted(tmp_path.glob("mpit_flight_*retry_exhausted*.json"))
        assert dumps, list(tmp_path.iterdir())
        stats = obs_flight.validate_dump(str(dumps[0]))
        assert stats["reason"] == "retry_exhausted"
        obj = json.load(open(dumps[0]))
        assert any(ev["kind"] == "retry_exhausted" for ev in obj["events"])

    def test_scheduler_watchdog_dumps_on_stall(self, obs_on, tmp_path,
                                               monkeypatch):
        """A queue that idles past stall_s without completing one task
        trips the watchdog exactly once per stall episode, and the dump
        carries the stuck task table."""
        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        sched = Scheduler(idle_usec=500, stall_s=0.01)

        def parked():
            while True:
                yield EXEC

        sched.spawn(parked(), name="stuck_service")
        deadline = time.monotonic() + 10
        fl = obs_flight.get_flight()
        while fl.last_dump_path is None and time.monotonic() < deadline:
            sched.ping_pass()
        assert fl.last_dump_path, "watchdog never dumped"
        stats = obs_flight.validate_dump(fl.last_dump_path)
        assert stats["reason"] == "scheduler_stall"
        obj = json.load(open(fl.last_dump_path))
        assert ["stuck_service", "EXEC"] in obj["tasks"]
        assert obs_on.counter("mpit_aio_stall_dumps_total").value == 1
        # one dump per episode: more idle passes must not re-dump
        first = fl.last_dump_path
        for _ in range(50):
            sched.ping_pass()
        assert fl.last_dump_path == first

    def test_eviction_dumps_flight(self, obs_on, tmp_path, monkeypatch):
        """A client that beats once and then goes silent is evicted on
        lease expiry — and the reaper leaves a reason=eviction dump."""
        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        servers, clients, threads, _ = launch_gang(
            1, 2, client_ft=FTConfig(heartbeat_s=0.01),
            server_ft=FTConfig(lease_ttl_s=0.15, rejoin=True))
        c0, c1 = clients
        starters = [threading.Thread(
            target=c.start,
            args=(np.zeros(16, np.float32), np.zeros(16, np.float32)),
            daemon=True) for c in clients]
        for t in starters:
            t.start()
        join_all(starters)  # both announced
        # The lease arms at the first beat: make c1 beat once (ping
        # emits + pumps the beacon), then go silent; c0 keeps beating
        # via ping until the reaper evicts c1 and dumps.
        for _ in range(20):
            c1.ping()
        time.sleep(0.02)
        deadline = time.monotonic() + 20
        while not any(tmp_path.glob("mpit_flight_*eviction*.json")):
            assert time.monotonic() < deadline, "eviction never dumped"
            c0.ping()
            time.sleep(0.005)
        c0.stop()
        c1.live.stop()
        join_all(threads)
        dump = sorted(tmp_path.glob("mpit_flight_*eviction*.json"))[0]
        stats = obs_flight.validate_dump(str(dump))
        assert stats["reason"] == "eviction"
        assert servers[0].leases.state(c1.rank) == "evicted"


# ---------------------------------------------------------------------------
# mpit top: exposition parsing + the aggregator read path


class TestTop:
    def test_parse_exposition(self):
        text = ('mpit_ps_grads_applied_total{rank="0"} 42\n'
                '# comment\n'
                'mpit_ps_grad_staleness_sum{client="2",rank="0"} 7\n'
                'mpit_ps_grad_staleness_count{client="2",rank="0"} 14\n'
                'garbage line\n'
                'mpit_shardctl_map_version 3\n')
        samples = obs_top.parse_exposition(text)
        assert obs_top.metric_sum(
            samples, "mpit_ps_grads_applied_total") == 42
        assert obs_top.metric_sum(
            samples, "mpit_ps_grads_applied_total", rank=0) == 42
        assert obs_top.hist_mean(
            samples, "mpit_ps_grad_staleness") == 0.5
        assert obs_top.metric_sum(samples, "mpit_shardctl_map_version") == 3

    def test_poll_rank_and_table(self, obs_on):
        obs_on.counter("mpit_ps_grads_applied_total", rank=0).inc(10)
        obs_on.counter("mpit_ps_params_served_total", rank=0).inc(5)
        obs_on.histogram("mpit_ps_grad_staleness", rank=0,
                         client=2).observe(2.0)
        obs_on.counter("mpit_ft_retries_total", rank=0).inc(3)
        srv = obs_statusd.StatusServer(0, rank=0, role="server")
        try:
            sample = obs_top.poll_rank("127.0.0.1", srv.port)
            assert sample["status"]["role"] == "server"
            row = obs_top._rank_row(0, sample, None, None)
            assert row["ops_total"] == 15
            assert row["staleness_mean"] == 2.0
            assert row["retries"] == 3
            table = obs_top.render_table([row, {"rank": 1, "up": False}])
            assert "server" in table and "(down)" in table
        finally:
            srv.close()

    def test_cli_once_json(self, obs_on, capsys):
        obs_on.counter("mpit_ps_grads_applied_total", rank=0).inc(1)
        srv = obs_statusd.StatusServer(0, rank=0, role="server")
        try:
            rc = obs_top.main(["--np", "1", "--base-port", str(srv.port),
                               "--iters", "1", "--json", "--min-up", "1"])
        finally:
            srv.close()
        assert rc == 0
        snap = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert snap["ranks"][0]["up"] and snap["ranks"][0]["ops_total"] == 1
        # a dead endpoint with --min-up fails loudly
        rc = obs_top.main(["--np", "1", "--base-port", str(srv.port),
                           "--iters", "1", "--json", "--min-up", "1"])
        assert rc == 1


# ---------------------------------------------------------------------------
# the merge subcommand: leftover parts from a crashed gang


class TestMergeSubcommand:
    def test_merge_assembles_leftover_parts(self, obs_on, tmp_path,
                                            capsys):
        rec = obs_spans.get_recorder()
        for i in range(2):
            sp = rec.op("GRAD", peer=0, side="client", seq=i + 1)
            sp.end("ok")
        base = str(tmp_path / "crashed.json")
        obs_trace.write_rank_trace(obs_trace.part_path(base, 0), 0,
                                   role="server")
        obs_trace.write_rank_trace(obs_trace.part_path(base, 3), 3,
                                   role="worker")
        assert obs_cli(["merge", base]) == 0
        stats = obs_trace.validate_trace(base)
        assert stats["pids"] == 2
        # parts kept by default (postmortem material)
        assert sorted(tmp_path.glob("crashed.json.rank*.json"))
        obj = json.load(open(base))
        assert set(obj["otherData"]["ranks"]) == {"0", "3"}

    def test_merge_without_parts_errors(self, tmp_path):
        assert obs_cli(["merge", str(tmp_path / "none.json")]) == 1

    def test_default_subcommand_still_validates(self, obs_on, tmp_path):
        path = obs_trace.write_rank_trace(str(tmp_path / "t.json"), 0)
        assert obs_cli([path]) == 0
        assert obs_cli(["validate", path]) == 0


# ---------------------------------------------------------------------------
# process-gang smoke: per-rank parts merged by the launcher (slow)


@pytest.mark.slow
def test_gang_merges_rank_traces(tmp_path, monkeypatch):
    """np=3 process gang with MPIT_OBS_TRACE: every child writes a part,
    the parent merges them, the merged trace validates and carries one
    pid per rank plus per-rank metrics riders."""
    from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

    trace_path = str(tmp_path / "gang_trace.json")
    monkeypatch.setenv("MPIT_OBS_TRACE", trace_path)
    cfg = LAUNCH_DEFAULTS.merged(
        np=3, opt="downpour", epochs=1, model="linear", side=8,
        batch=64, master_freq=2, device_policy="cpu",
    )
    results = launch_processes(cfg, timeout=600)
    assert set(results) == {0, 1, 2}
    stats = obs_trace.validate_trace(trace_path)
    assert stats["pids"] == 3 and stats["events"] > 0
    obj = json.load(open(trace_path))
    ranks = obj["otherData"]["ranks"]
    assert set(ranks) == {"0", "1", "2"}
    server_metrics = ranks["0"]["metrics"]
    assert any(k.startswith("mpit_ps_grads_applied_total")
               for k in server_metrics)
    assert not list(tmp_path.glob("gang_trace.json.rank*")), \
        "part files should be cleaned up after the merge"
