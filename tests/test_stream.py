"""Pipelined streaming transfers (FLAG_CHUNKED, docs/PROTOCOL.md §12).

The contract under test: chunking a shard transfer into K independent
frames changes *when* bytes move and applies run, and nothing else —
final params are BITWISE equal to unchunked transfers, for every codec,
under any drop/dup/delay fault pattern, including the int8
error-feedback residual.  Chunk-level faults come free from the
message-atomic FaultPlan seam: each chunk is its own message, so
``drop_every=3`` on the GRAD channel drops individual *chunks*.

Topology notes mirror tests/test_ft.py: client-side plans fault the
chunk data channels (GRAD / PARAM_REQ / PARAM_PUSH), server-side plans
the per-chunk acks and reply-chunk streams (GRAD_ACK / PARAM /
PARAM_PUSH_ACK).  Lockstep rounds pin the cross-client apply order so
faulty and fault-free runs are bitwise-comparable.
"""

import threading
import time

import numpy as np
import pytest

from mpit_tpu.aio import TaskError
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import (
    DUP,
    FRESH,
    STALE,
    DedupTable,
    FaultPlan,
    FaultyTransport,
    FTConfig,
    PacedTransport,
    RetryExhausted,
    chunk_elems_for,
    chunk_spans,
    chunk_stride,
)
from mpit_tpu.ps import ParamClient, ParamServer, tags

DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})
REPLY_TAGS = frozenset({tags.GRAD_ACK, tags.PARAM, tags.PARAM_PUSH_ACK})

#: fast retry posture for LocalRouter-speed gangs; chunk_bytes=8192 cuts
#: a f32 shard at 2048-element boundaries (block-aligned by fiat).
def stream_ft(chunk_bytes=8192, deadline=2.0, retries=10):
    return FTConfig(op_deadline_s=deadline, max_retries=retries,
                    backoff_base_s=0.005, backoff_cap_s=0.02,
                    chunk_bytes=chunk_bytes)


def join_all(threads, timeout=60):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# wire units


class TestChunkWire:
    def test_chunk_elems_block_aligned(self):
        assert chunk_elems_for(8192, 4) == 2048
        assert chunk_elems_for(4 << 20, 4) == 1024 * 1024
        assert chunk_elems_for(1, 4) == 1024  # floor: one block
        assert chunk_elems_for(5000, 4) == 1024  # rounds DOWN to blocks
        assert chunk_elems_for(8192, 8) == 1024

    def test_chunk_spans_cover_exactly(self):
        spans = chunk_spans(5000, 2048)
        assert spans == [(0, 2048), (2048, 4096), (4096, 5000)]
        assert chunk_spans(4096, 2048) == [(0, 2048), (2048, 4096)]
        assert chunk_spans(100, 2048) == [(0, 100)]

    def test_chunk_stride_aligned(self):
        assert chunk_stride(32, 8192) % 64 == 0
        assert chunk_stride(32, 8192) >= 32 + 8192

    @pytest.mark.parametrize("codec_name", ["none", "bf16", "int8"])
    def test_chunk_frames_bit_identical_to_full_frame(self, codec_name):
        """Per-chunk encode == the corresponding regions of the
        whole-shard encode (gather_chunk), and chunked decode == full
        decode — the §12.2 block-boundary invariant, residual fold
        included."""
        codec = codec_mod.get(codec_name)
        rng = np.random.default_rng(7)
        size = 5000
        x = rng.normal(size=size).astype(np.float32)
        full = np.zeros(codec.wire_nbytes(size), np.uint8)
        r_full = np.zeros(size, np.float32)
        codec.encode_into(x, full,
                          residual=r_full if codec.uses_residual else None)
        r_chunk = np.zeros(size, np.float32)
        out_full = np.zeros(size, np.float32)
        codec.decode_into(full, out_full)
        out_chunk = np.zeros(size, np.float32)
        for lo, hi in chunk_spans(size, 2048):
            frame = np.zeros(codec.wire_nbytes(hi - lo), np.uint8)
            codec.encode_into(
                x[lo:hi], frame,
                residual=r_chunk[lo:hi] if codec.uses_residual else None)
            ref = np.zeros_like(frame)
            codec_mod.gather_chunk(codec, full, size, lo, hi, ref)
            np.testing.assert_array_equal(frame, ref)
            codec.decode_into(frame, out_chunk[lo:hi])
            # scatter is gather's exact inverse
            back = np.zeros_like(full)
            codec_mod.scatter_chunk(codec, back, size, lo, hi, frame)
            np.testing.assert_array_equal(
                back[back != 0], full[back != 0])
        np.testing.assert_array_equal(out_full, out_chunk)
        if codec.uses_residual:
            np.testing.assert_array_equal(r_full, r_chunk)

    def test_unaligned_chunk_start_rejected(self):
        codec = codec_mod.get("int8")
        with pytest.raises(ValueError, match="aligned"):
            codec.chunk_regions(5000, 100, 2048)


# ---------------------------------------------------------------------------
# per-(op, chunk) dedup


class TestChunkDedup:
    def test_admit_commit_cycle(self):
        t = DedupTable()
        assert t.admit_chunk(1, tags.GRAD, 0, 1, 0, 3) == (FRESH, False)
        assert t.admit_chunk(1, tags.GRAD, 0, 1, 0, 3) == (DUP, False)
        assert t.admit_chunk(1, tags.GRAD, 0, 1, 2, 3) == (FRESH, False)
        assert t.admit_chunk(1, tags.GRAD, 0, 1, 1, 3) == (FRESH, True)
        # every chunk of the committed op now DUPs (re-ack path)
        assert t.admit_chunk(1, tags.GRAD, 0, 1, 1, 3) == (DUP, False)
        assert t.is_committed(1, tags.GRAD, 0, 1)
        # next op starts clean
        assert t.admit_chunk(1, tags.GRAD, 0, 2, 0, 3) == (FRESH, False)
        assert not t.is_committed(1, tags.GRAD, 0, 2)

    def test_stale_epoch_and_abandoned_partial(self):
        t = DedupTable()
        t.admit_chunk(1, tags.GRAD, 1, 1, 0, 2)
        assert t.admit_chunk(1, tags.GRAD, 0, 9, 0, 2)[0] == STALE
        # a newer seq abandons the in-flight partial silently
        assert t.admit_chunk(1, tags.GRAD, 1, 2, 0, 2) == (FRESH, False)
        assert t.admit_chunk(1, tags.GRAD, 1, 2, 1, 2) == (FRESH, True)

    def test_partial_state_roundtrip_grad_only(self):
        t = DedupTable()
        t.admit_chunk(1, tags.GRAD, 0, 5, 1, 3)
        t.admit_chunk(1, tags.PARAM_PUSH, 0, 2, 0, 3)
        part = t.partial_state(tags={tags.GRAD})
        assert list(part) == [f"1:{tags.GRAD}"]
        fresh = DedupTable()
        fresh.restore_partial(part)
        # the restored partial dedups the already-applied chunk and
        # commits on the remainder — the restart consistency cut
        assert fresh.admit_chunk(1, tags.GRAD, 0, 5, 1, 3) == (DUP, False)
        assert fresh.admit_chunk(1, tags.GRAD, 0, 5, 0, 3) == (FRESH, False)
        assert fresh.admit_chunk(1, tags.GRAD, 0, 5, 2, 3) == (FRESH, True)


# ---------------------------------------------------------------------------
# gang harness (test_ft.py idiom, chunked)


def launch_stream(nservers, nclients, client_ft, client_plans=None,
                  server_plan=None, rule="add", codec=None,
                  pace_mbs=0.0):
    n = nservers + nclients
    router = LocalRouter(n)
    sranks = list(range(nservers))
    cranks = list(range(nservers, n))
    servers, threads = [], []
    for r in sranks:
        ep = router.endpoint(r)
        if pace_mbs:
            ep = PacedTransport(ep, pace_mbs)
        if server_plan is not None:
            ep = FaultyTransport(ep, server_plan)
        servers.append(ParamServer(r, cranks, ep, rule=rule,
                                   ft=FTConfig(rejoin=True)))
        threads.append(threading.Thread(target=servers[-1].start,
                                        daemon=True))
    for t in threads:
        t.start()
    clients = []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        if pace_mbs:
            ep = PacedTransport(ep, pace_mbs)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        clients.append(ParamClient(r, sranks, ep,
                                   seed_servers=(r == cranks[0]),
                                   codec=codec, ft=client_ft))
    return servers, clients, threads


def run_gang(nservers, nclients, client_ft, rounds=3, size=10000,
             client_plans=None, server_plan=None, rule="add", codec=None,
             pace_mbs=0.0, seed=42):
    """Seed, run lockstep rounds, read back: returns (final params of
    client 0, stats)."""
    rng = np.random.default_rng(seed)
    w0 = rng.normal(size=size).astype(np.float32)
    gtab = rng.normal(size=(nclients, max(rounds, 1), size)).astype(
        np.float32)
    servers, clients, threads = launch_stream(
        nservers, nclients, client_ft, client_plans=client_plans,
        server_plan=server_plan, rule=rule, codec=codec,
        pace_mbs=pace_mbs)
    params, starters = [], []
    for i, c in enumerate(clients):
        p = w0.copy() if i == 0 else np.zeros(size, np.float32)
        g = np.zeros(size, np.float32)
        params.append((p, g))
        starters.append(threading.Thread(target=c.start, args=(p, g),
                                         daemon=True))
    for t in starters:
        t.start()
    join_all(starters)
    for r in range(rounds):
        for i, c in enumerate(clients):
            params[i][1][:] = gtab[i, r]
            c.async_send_grad()
            c.wait()
    clients[0].async_recv_param()
    clients[0].wait()
    stats = {
        "applied": sum(s.grads_applied for s in servers),
        "dups": sum(s.dup_ops for s in servers),
        "retries": sum(c.retries for c in clients),
    }
    for c in clients:
        c.stop()
    join_all(threads)
    return params[0][0].copy(), stats


# ---------------------------------------------------------------------------
# end-to-end bitwise equality


class TestChunkedBitwise:
    @pytest.mark.parametrize("codec_name", ["none", "bf16", "int8"])
    @pytest.mark.parametrize("size", [10000, 16384])
    def test_chunked_equals_unchunked(self, codec_name, size):
        """Fault-free: a chunked gang's final params equal the
        unchunked framed gang's bitwise — tailed (10000 ⇒ 5000/server)
        and block-multiple (16384) shards exercise both roundings of
        the fused-vs-host chunk apply (§12.5)."""
        clean, _ = run_gang(2, 2, stream_ft(chunk_bytes=0), size=size,
                            codec=codec_name)
        chunked, st = run_gang(2, 2, stream_ft(), size=size,
                               codec=codec_name)
        np.testing.assert_array_equal(clean, chunked)
        assert st["retries"] == 0

    def test_chunked_equals_unchunked_stateful_rule(self):
        clean, _ = run_gang(2, 2, stream_ft(chunk_bytes=0), rule="rmsprop",
                            codec="int8")
        chunked, _ = run_gang(2, 2, stream_ft(), rule="rmsprop",
                              codec="int8")
        np.testing.assert_array_equal(clean, chunked)

    def test_chunk_drop_dup_matrix_bitwise(self):
        """The §12 acceptance matrix: every 3rd chunk message dropped +
        every 4th duplicated client-side, every 5th ack/reply chunk
        dropped + every 3rd duplicated server-side — final params must
        equal the fault-free *unchunked* run bitwise, with retries and
        dups actually flowing."""
        clean, _ = run_gang(2, 2, stream_ft(chunk_bytes=0))
        client_plans = {
            i: FaultPlan(seed=i, drop_every=3, dup_every=4, tags=DATA_TAGS)
            for i in range(2)
        }
        server_plan = FaultPlan(seed=9, drop_every=5, dup_every=3,
                                tags=REPLY_TAGS)
        faulty, st = run_gang(
            2, 2, stream_ft(deadline=0.3), client_plans=client_plans,
            server_plan=server_plan)
        np.testing.assert_array_equal(clean, faulty)
        assert st["retries"] > 0, "the plan never forced a chunk resend?"
        assert st["dups"] > 0, "no duplicate chunk was ever re-acked?"

    def test_int8_error_feedback_exact_under_chunk_faults(self):
        clean, _ = run_gang(2, 2, stream_ft(chunk_bytes=0), codec="int8")
        client_plans = {
            i: FaultPlan(seed=31 + i, drop_every=3, dup_every=5,
                         tags=DATA_TAGS)
            for i in range(2)
        }
        faulty, st = run_gang(2, 2, stream_ft(deadline=0.3),
                              client_plans=client_plans, codec="int8")
        np.testing.assert_array_equal(clean, faulty)
        assert st["retries"] > 0

    def test_unsplittable_rule_refused_loudly(self):
        """Adam's scalar step counter cannot split across chunks — the
        negotiation must refuse, not corrupt quietly (§12.5)."""
        with pytest.raises((TaskError, RetryExhausted, AssertionError)):
            run_gang(1, 1, stream_ft(deadline=0.3, retries=2),
                     rounds=1, rule="adam")

    def test_paced_link_runs_clean(self):
        """The PacedTransport link model (bench/smoke seam) preserves
        correctness: a chunked gang over a modeled 200 MB/s link stays
        bitwise-equal to the unpaced unchunked control."""
        clean, _ = run_gang(1, 1, stream_ft(chunk_bytes=0), rounds=2)
        paced, _ = run_gang(1, 1, stream_ft(deadline=5.0), rounds=2,
                            pace_mbs=200.0)
        np.testing.assert_array_equal(clean, paced)


# ---------------------------------------------------------------------------
# legacy interop


class TestLegacyInterop:
    def test_no_flag_pairs_byte_for_byte_unchanged(self):
        """A pair that never negotiates FLAG_CHUNKED produces the exact
        pre-§12 wire: v3 announcements, whole-frame messages, 2-word
        acks.  (Byte-compat is asserted at the message level via the
        router mailboxes.)"""
        router = LocalRouter(2)
        sent = []
        ep = router.endpoint(1)
        inner_isend = ep.isend

        def spy(data, dst, tag):
            sent.append((tag, np.asarray(data).nbytes
                         if isinstance(data, np.ndarray) else len(data)))
            return inner_isend(data, dst, tag)

        ep.isend = spy
        server = ParamServer(0, [1], router.endpoint(0), rule="add")
        th = threading.Thread(target=server.start, daemon=True)
        th.start()
        ft = FTConfig(op_deadline_s=5.0)  # framed, NOT chunked
        client = ParamClient(1, [0], ep, seed_servers=True, ft=ft)
        size = 4096
        client.start(np.zeros(size, np.float32),
                     np.ones(size, np.float32))
        client.async_send_grad()
        client.wait()
        client.stop()
        join_all([th])
        init = [n for t, n in sent if t == tags.INIT]
        assert init == [40], f"framed non-chunked INIT must stay v3: {init}"
        grads = [n for t, n in sent if t == tags.GRAD]
        assert grads == [16 + 4 * size], (
            "non-chunked GRAD must stay one whole [hdr|body] frame")

    def test_chunked_init_is_v5(self):
        router = LocalRouter(2)
        sent = []
        ep = router.endpoint(1)
        inner_isend = ep.isend

        def spy(data, dst, tag):
            sent.append((tag, np.asarray(data).nbytes
                         if isinstance(data, np.ndarray) else len(data)))
            return inner_isend(data, dst, tag)

        ep.isend = spy
        server = ParamServer(0, [1], router.endpoint(0), rule="add")
        th = threading.Thread(target=server.start, daemon=True)
        th.start()
        client = ParamClient(1, [0], ep, seed_servers=True, ft=stream_ft())
        size = 4096
        client.start(np.zeros(size, np.float32),
                     np.ones(size, np.float32))
        client.async_send_grad()
        client.wait()
        client.stop()
        join_all([th])
        init = [n for t, n in sent if t == tags.INIT]
        assert init == [48], f"chunked INIT must be v5 (48 B): {init}"
        grads = [(t, n) for t, n in sent if t == tags.GRAD]
        # 4096 f32 at 2048-elem chunks = 2 uniform frames
        assert len(grads) == 2
        assert len({n for _t, n in grads}) == 1, "chunk frames not uniform"

    def test_readonly_chunked_announce_rejected(self):
        from mpit_tpu.ft import FLAG_CHUNKED, FLAG_FRAMED, FLAG_READONLY

        server = ParamServer(0, [1], LocalRouter(3).endpoint(0),
                             rule="add", reader_ranks=[2])
        with pytest.raises(ValueError, match="READONLY"):
            server._negotiate(2, np.asarray(
                [0, 1024, 0, 0,
                 FLAG_FRAMED | FLAG_READONLY | FLAG_CHUNKED, 1024],
                np.int64).tobytes())


# ---------------------------------------------------------------------------
# server restart mid-stream (checkpoint consistency cut)


class TestChunkedRestart:
    def test_checkpoint_carries_grad_chunk_partials(self, tmp_path):
        """A checkpoint cut between chunk applies persists the partial
        admission set next to the partially-updated params, so a
        restarted server re-acks the applied chunks and the client
        completes the op by resending only the rest (§12.6)."""
        from mpit_tpu.utils.checkpoint import load_server_state

        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0), rule="add",
                             ft=FTConfig(rejoin=True))
        # Negotiate a chunked client by hand (INIT v5).
        from mpit_tpu.ft import FLAG_CHUNKED, FLAG_FRAMED, init_v5
        codec = server._negotiate(1, np.asarray(init_v5(
            0, 4096, 0, 0, FLAG_FRAMED | FLAG_CHUNKED, 2048)).tobytes())
        server._alloc_client(1, codec)
        # Admit + apply chunk 0 of seq 1 only.
        v, done = server.dedup.admit_chunk(1, tags.GRAD, 0, 1, 0, 2)
        assert (v, done) == (FRESH, False)
        grad = np.ones(2048, np.float32)
        server._apply_chunk(1, codec, grad.view(np.uint8), 0, 2048,
                            commit=False)
        path = server.save_state(str(tmp_path))
        _off, _size, _param, _state, meta = load_server_state(path)
        assert meta["dedup_chunks"] == {f"1:{tags.GRAD}": [0, 1, 2, [0]]}
        restarted = ParamServer(0, [1], router.endpoint(0), rule="add",
                                ft=FTConfig(rejoin=True))
        restarted.restore_state(path)
        # The resent chunk 0 dedups; chunk 1 completes the op.
        assert restarted.dedup.admit_chunk(1, tags.GRAD, 0, 1, 0, 2) == \
            (DUP, False)
        assert restarted.dedup.admit_chunk(1, tags.GRAD, 0, 1, 1, 2) == \
            (FRESH, True)
        assert restarted._chunk.get(1) == 2048
        np.testing.assert_array_equal(
            np.asarray(restarted.param)[:2048], grad)


# ---------------------------------------------------------------------------
# dplane chunk-apply parity


class TestHbmChunkApply:
    @pytest.mark.parametrize("codec_name", ["none", "int8"])
    def test_chunk_apply_matches_whole_apply(self, codec_name):
        """HbmSlot.apply_wire_chunk over every chunk == apply_wire of
        the whole frame, bitwise, for a block-multiple slot (the fused
        chunk rounding case) — and the donated update still consumes
        its buffers."""
        from mpit_tpu.dplane.hbm import HbmSlot, PlaneConfig
        from mpit_tpu.optim.rules import make as make_rule

        codec = codec_mod.get(codec_name)
        size = 4096
        rng = np.random.default_rng(3)
        g = rng.normal(size=size).astype(np.float32)
        wire = np.zeros(codec.wire_nbytes(size), np.uint8)
        codec.encode_into(g, wire)

        whole = HbmSlot(size, make_rule("add"), config=PlaneConfig())
        if codec.identity:
            whole.apply_wire(codec, wire.view(np.float32))
        else:
            whole.apply_wire(codec, codec.split_wire(wire, size))

        chunked = HbmSlot(size, make_rule("add"), config=PlaneConfig())
        spans = chunk_spans(size, 2048)
        for k, (lo, hi) in enumerate(spans):
            frame = np.zeros(codec.wire_nbytes(hi - lo), np.uint8)
            codec_mod.gather_chunk(codec, wire, size, lo, hi, frame)
            payload = (frame.view(np.float32) if codec.identity
                       else codec.split_wire(frame, hi - lo))
            chunked.apply_wire_chunk(codec, payload, lo, hi - lo,
                                     commit=(k == len(spans) - 1))
        assert chunked.version == whole.version == 1
        np.testing.assert_array_equal(np.asarray(whole.param),
                                      np.asarray(chunked.param))


# ---------------------------------------------------------------------------
# the §12 property test (ISSUE 13 satellite): random chunk-level plans


@pytest.mark.parametrize("codec_name", ["none", "bf16", "int8"])
@pytest.mark.parametrize("seed", range(5))
def test_property_chunk_faults_bitwise_or_loud(seed, codec_name):
    """Seed-deterministic random {drop, dup, delay} plans at CHUNK
    granularity (each chunk is its own message) across ≥5 seeds × every
    codec: the run either completes with final params bitwise-equal to
    the fault-free *unchunked* control — int8 error feedback included —
    or fails loudly (RetryExhausted / TaskError).  Never a hang: the
    worker runs under a hard timeout."""
    rng = np.random.default_rng(seed * 1000 + codec_mod.get(
        codec_name).wire_id)
    nclients = int(rng.integers(1, 3))
    rounds = 2
    size = int(rng.choice([6144, 10000]))  # block-multiple AND tailed

    clean, _ = run_gang(2, nclients, stream_ft(chunk_bytes=0),
                        rounds=rounds, size=size, codec=codec_name,
                        seed=seed)

    client_plans = {
        i: FaultPlan(seed=seed * 17 + i, drop_rate=0.10, dup_rate=0.08,
                     delay_rate=0.15, delay_polls=4, tags=DATA_TAGS)
        for i in range(nclients)
    }
    server_plan = FaultPlan(seed=seed * 31 + 7, drop_rate=0.08,
                            dup_rate=0.08, delay_rate=0.15, delay_polls=4,
                            tags=REPLY_TAGS)
    box: dict = {}

    def run():
        try:
            box["params"], box["stats"] = run_gang(
                2, nclients,
                stream_ft(deadline=0.3, retries=8),
                rounds=rounds, size=size, client_plans=client_plans,
                server_plan=server_plan, codec=codec_name, seed=seed)
        except (TaskError, RetryExhausted, AssertionError) as exc:
            box["error"] = exc  # loud is an acceptable outcome

    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    worker.join(120)  # the hard timeout: a hang is the forbidden outcome
    assert not worker.is_alive(), (
        "chunked faulty run HUNG (never-hang contract broken)")
    if "params" in box:
        np.testing.assert_array_equal(clean, box["params"])
    else:
        assert "error" in box  # failed loudly


# ---------------------------------------------------------------------------
# PacedTransport model units


class TestPacedTransport:
    def test_paces_serially_and_preserves_fifo(self):
        router = LocalRouter(2)
        paced = PacedTransport(router.endpoint(0), rate_mbs=1.0,
                               min_bytes=0)
        rx = router.endpoint(1)
        a = np.zeros(1 << 20, np.uint8)  # 1 MB = 1 s of modeled link
        t0 = time.monotonic()
        h1 = paced.isend(a, 1, 50)
        h2 = paced.isend(a[:1024], 1, 50)
        assert not rx.iprobe(0, 50)
        # pump below the due time: still on the link
        paced.test(h1)
        assert not h1.done and not rx.iprobe(0, 50)
        # tiny messages queue BEHIND the big one (serial link)
        deadline = time.monotonic() + 10
        while not (paced.test(h1) and paced.test(h2)):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert time.monotonic() - t0 >= 1.0
        assert rx.iprobe(0, 50)

    def test_min_bytes_pass_through(self):
        router = LocalRouter(2)
        paced = PacedTransport(router.endpoint(0), rate_mbs=0.001,
                               min_bytes=4096)
        h = paced.isend(np.zeros(16, np.uint8), 1, 50)
        while not paced.test(h):
            pass
        assert router.endpoint(1).iprobe(0, 50)
