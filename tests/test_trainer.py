"""Trainer + launcher tests: single-process (claunch analog) and threaded
multi-role topologies (mlaunch analog) on the in-process router.
"""

import threading

import numpy as np
import pytest

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.data.mnist import load_mnist
from mpit_tpu.train.launch import LAUNCH_DEFAULTS, assign_roles, run_rank, server_rule_for
from mpit_tpu.train.trainer import MnistTrainer, TRAINER_DEFAULTS
from mpit_tpu.utils.config import Config


@pytest.fixture(scope="module")
def small_data():
    (x_train, y_train, x_test, y_test), source = load_mnist(side=8)
    # keep it tiny for 1-CPU test speed
    return (x_train[:512], y_train[:512], x_test[:256], y_test[:256])


class TestAssignRoles:
    def test_parity_split(self):
        sranks, cranks, tester = assign_roles(12)
        assert sranks == [0, 2, 4, 6, 8, 10]
        assert cranks == [1, 3, 5, 7, 9, 11]
        assert tester is None

    def test_master_freq_3(self):
        sranks, cranks, _ = assign_roles(6, master_freq=3)
        assert sranks == [0, 3]
        assert cranks == [1, 2, 4, 5]

    def test_tester_last(self):
        sranks, cranks, tester = assign_roles(5, tester="last")
        assert tester == 4
        assert 4 not in sranks and 4 not in cranks

    def test_tester_first(self):
        sranks, cranks, tester = assign_roles(5, tester="first")
        assert tester == 0
        assert 0 not in sranks and 0 not in cranks

    def test_degenerate_raises(self):
        with pytest.raises(ValueError):
            assign_roles(1)


class TestServerRule:
    def test_stateful_rules_match_opt(self):
        assert server_rule_for(Config(opt="adam", lr=0.1)).apply is not None

    def test_delta_optimizers_use_add(self):
        from mpit_tpu.optim.rules import add_apply

        rule = server_rule_for(Config(opt="eamsgd", lr=0.1))
        assert rule.apply is add_apply


class TestLocalTrainer:
    def test_msgd_learns(self, small_data):
        cfg = TRAINER_DEFAULTS.merged(
            model="linear", opt="msgd", lr=0.3, mom=0.9, epochs=3,
            batch=64, side=8,
        )
        trainer = MnistTrainer(cfg, data=small_data)
        err0 = trainer.test_error()
        result = trainer.run()
        assert result["final_test_err"] < err0
        assert result["final_test_err"] < 0.5
        assert len(result["history"]) == 3
        assert "feval" in result["timers"]

    def test_comm_optimizer_without_client_raises(self, small_data):
        cfg = TRAINER_DEFAULTS.merged(opt="downpour", side=8, epochs=1)
        trainer = MnistTrainer(cfg, data=small_data)  # eval-only use is fine
        with pytest.raises(ValueError, match="parameter client"):
            trainer.run()


def run_topology(size, cfg, data, timeout=300):
    """Run all ranks of a topology on threads over the in-process router."""
    router = LocalRouter(size)
    results = {}
    errors = {}

    def target(rank):
        try:
            results[rank] = run_rank(rank, size, cfg, router.endpoint(rank), data=data)
        except BaseException as exc:  # noqa: BLE001
            errors[rank] = exc

    threads = [threading.Thread(target=target, args=(r,), daemon=True) for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    # A crashed rank starves its peers, so surface rank errors first.
    if errors:
        raise next(iter(errors.values()))
    assert not any(t.is_alive() for t in threads), f"topology hung; done={list(results)}"
    return results


class TestTopologies:
    def test_downpour_np4(self, small_data):
        cfg = LAUNCH_DEFAULTS.merged(
            np=4, opt="downpour", lr=0.2, su=1, epochs=1, batch=64, side=8,
        )
        results = run_topology(4, cfg, small_data)
        roles = {r: res["role"] for r, res in results.items()}
        assert roles == {0: "server", 1: "worker", 2: "server", 3: "worker"}
        for rank in (0, 2):
            assert results[rank]["grads_applied"] > 0
        for rank in (1, 3):
            assert results[rank]["final_test_err"] < 0.8

    def test_eamsgd_np4(self, small_data):
        cfg = LAUNCH_DEFAULTS.merged(
            np=4, opt="eamsgd", lr=0.2, mom=0.9, mva=0.45, su=5,
            epochs=1, batch=64, side=8,
        )
        results = run_topology(4, cfg, small_data)
        workers = [res for res in results.values() if res["role"] == "worker"]
        assert len(workers) == 2
        assert all(w["final_test_err"] < 0.8 for w in workers)

    def test_eamsgd_np4_int8_codec_converges(self, small_data):
        """The flagship EASGD topology with quantized shard transfer
        (codec=int8 pins the servers AND drives the clients) must reach
        the same test-error bar as the uncompressed run above — the
        client-held error-feedback residual carries the quantization
        error across sync rounds."""
        cfg = LAUNCH_DEFAULTS.merged(
            np=4, opt="eamsgd", lr=0.2, mom=0.9, mva=0.45, su=5,
            epochs=1, batch=64, side=8, codec="int8",
        )
        results = run_topology(4, cfg, small_data)
        workers = [res for res in results.values() if res["role"] == "worker"]
        assert len(workers) == 2
        assert all(w["final_test_err"] < 0.8 for w in workers)
        assert all(res["grads_applied"] > 0 for res in results.values()
                   if res["role"] == "server")

    def test_downpour_np4_bf16_codec(self, small_data):
        cfg = LAUNCH_DEFAULTS.merged(
            np=4, opt="downpour", lr=0.2, su=1, epochs=1, batch=64, side=8,
            codec="bf16",
        )
        results = run_topology(4, cfg, small_data)
        workers = [res for res in results.values() if res["role"] == "worker"]
        assert all(w["final_test_err"] < 0.8 for w in workers)

    def test_tester_role(self, small_data, tmp_path):
        cfg = LAUNCH_DEFAULTS.merged(
            np=3, opt="downpour", lr=0.2, su=1, epochs=1, batch=64, side=8,
            tester="last", tester_rounds=3, tester_interval=0.05,
            ckpt_dir=str(tmp_path),
        )
        results = run_topology(3, cfg, small_data)
        tester = results[2]
        assert tester["role"] == "tester"
        assert tester["best_test_err"] <= 1.0
        assert len(tester["history"]) == 3
        assert list(tmp_path.glob("ckpt_*.npz")), "tester should checkpoint"

    def test_adam_server_stateful_np2(self, small_data):
        cfg = LAUNCH_DEFAULTS.merged(
            np=2, opt="adam", lr=1e-3, su=1, epochs=1, batch=64, side=8,
        )
        results = run_topology(2, cfg, small_data)
        assert results[0]["role"] == "server" and results[0]["grads_applied"] > 0
        assert results[1]["role"] == "worker"


class TestDevicePolicy:
    def test_overrides_shapes(self):
        from mpit_tpu.train.launch import LAUNCH_DEFAULTS, device_env_overrides

        cfg = LAUNCH_DEFAULTS.merged(np=4)
        assert device_env_overrides(cfg, 4) == {}
        cfg = cfg.merged(device_policy="cpu")
        ov = device_env_overrides(cfg, 4)
        assert set(ov) == {0, 1, 2, 3}
        assert all(v == {"JAX_PLATFORMS": "cpu"} for v in ov.values())
        cfg = cfg.merged(device_policy="workers_accel")
        ov = device_env_overrides(cfg, 4)
        # master_freq=2: even ranks are servers; of the clients {1, 3}
        # only the first keeps the accelerator -> all but rank 1 forced.
        assert set(ov) == {0, 2, 3}
        import pytest as _pytest
        with _pytest.raises(ValueError, match="device_policy"):
            device_env_overrides(cfg.merged(device_policy="gpu4"), 4)

    @pytest.mark.slow
    def test_gang_applies_policy(self, monkeypatch):
        """np=2 gang with device_policy=cpu: children report the forced
        platform.  The parent's inherited JAX_PLATFORMS is removed so the
        assertion can only pass through the env_overrides plumbing (on an
        accelerator host a broken override would surface as a non-cpu
        platform or a chip-contention failure)."""
        from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        cfg = LAUNCH_DEFAULTS.merged(
            np=2, opt="downpour", epochs=1, model="linear", side=8,
            batch=64, device_policy="cpu", master_freq=2,
        )
        results = launch_processes(cfg, timeout=600)
        assert set(results) == {0, 1}
        assert all(r.get("platform") == "cpu" for r in results.values())


@pytest.mark.slow
class TestServerCkptResumeGang:
    def test_two_session_resume(self, tmp_path):
        """Session 1 trains with periodic server checkpoints; session 2
        resumes from them (servers restore, no client seeding) and keeps
        training — the launcher-level resume flow the in-process PS tests
        cover at the API level."""
        from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

        base = LAUNCH_DEFAULTS.merged(
            np=3, opt="downpour", epochs=1, model="linear", side=8,
            batch=64, master_freq=2, device_policy="cpu",
            server_ckpt_dir=str(tmp_path), server_ckpt_interval=0.2,
        )
        r1 = launch_processes(base, timeout=600)
        servers1 = {r: v for r, v in r1.items() if v["role"] == "server"}
        assert servers1 and all(v["ckpts_written"] >= 1 for v in servers1.values())
        for r in servers1:
            assert (tmp_path / f"server{r}_latest.npz").exists()

        r2 = launch_processes(base.merged(resume=True), timeout=600)
        servers2 = {r: v for r, v in r2.items() if v["role"] == "server"}
        workers2 = [v for v in r2.values() if v["role"] == "worker"]
        # Restored moment/param state: grads_applied continues the count
        # from session 1 instead of restarting at the session's own total.
        for r, v in servers2.items():
            assert v["grads_applied"] > servers1[r]["grads_applied"]
        assert workers2 and all("final_test_err" in w for w in workers2)
