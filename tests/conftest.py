"""Test harness: force an 8-virtual-device CPU JAX platform.

Multi-chip code paths (mesh sharding, collectives, role-split parallelism)
are exercised without TPU hardware by asking XLA for 8 host devices — the
analog of the reference running N MPI ranks on one host over the
shared-memory transport as its "fake backend" (reference README.md:28-31,
SURVEY.md section 4).  Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override any preset TPU/axon platform
# 8 mesh devices + pool headroom: XLA:CPU sizes the client thread pool to
# the virtual device count, and a program sharded over every device then
# deadlocks its collective rendezvous whenever any pool thread is busy
# with other work (fatal abort after 40 s — docs/xla_cpu_rendezvous_abort.md).
# The extra devices are never meshed (MPIT_MESH_DEVICES caps the pool via
# mpit_tpu.utils.platform.default_devices); they only widen the pool.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=12"
).strip()
os.environ["MPIT_MESH_DEVICES"] = "8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The environment may pre-import jax at interpreter startup (e.g. a TPU
# plugin registered from sitecustomize), in which case the env vars above
# are read too late — force the platform through the live config instead.
# Safe as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the whole suite: the expensive
# tests are compile-dominated (sharded ring-attention grad graphs), and
# re-running the suite recompiles identical programs.  Same cache dir as
# the trainers (repo-local .jax_cache, gitignored) — a fresh clone runs
# cold once.  Disable with MPIT_TEST_COMPILE_CACHE=0.
if os.environ.get("MPIT_TEST_COMPILE_CACHE", "1") != "0":
    from mpit_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (gang/integration scale)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: integration-scale test (process gangs, long training loops) "
        "skipped by default; enable with --runslow",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow: run with --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
