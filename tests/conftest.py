"""Test harness: force an 8-virtual-device CPU JAX platform.

Multi-chip code paths (mesh sharding, collectives, role-split parallelism)
are exercised without TPU hardware by asking XLA for 8 host devices — the
analog of the reference running N MPI ranks on one host over the
shared-memory transport as its "fake backend" (reference README.md:28-31,
SURVEY.md section 4).  Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override any preset TPU/axon platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The environment may pre-import jax at interpreter startup (e.g. a TPU
# plugin registered from sitecustomize), in which case the env vars above
# are read too late — force the platform through the live config instead.
# Safe as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
