"""Ring attention == full attention, on the 8-virtual-device CPU mesh.

Exactness is the contract: the ring computes full (not windowed)
attention via online-softmax partial merging, so outputs and gradients
must match the dense reference to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import TinyDecoder, default_attn
from mpit_tpu.models.flat import flatten_module
from mpit_tpu.ops import attention_reference
from mpit_tpu.parallel import ring_attention, sp_mesh

B, L, H, D = 2, 64, 2, 16


@pytest.fixture(scope="module")
def mesh():
    return sp_mesh()


def _qkv(rng, shape=(B, L, H, D)):
    return tuple(
        jnp.asarray(rng.normal(size=shape) * 0.5, jnp.float32) for _ in range(3)
    )


def _ref(q, k, v, causal):
    qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    return attention_reference(qh, kh, vh, causal=causal).transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_ring_matches_full(rng, mesh, causal, impl):
    q, k, v = _qkv(rng)
    ring = ring_attention(mesh, causal=causal, impl=impl)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, causal)), atol=3e-5
    )


@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_ring_grads_match_full(rng, mesh, impl):
    q, k, v = _qkv(rng)
    ring = ring_attention(mesh, causal=True, impl=impl)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, True) ** 2)

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_uneven_batch_heads(rng, mesh):
    # One head, odd batch: exercises the vmap paths, L still divides n.
    q, k, v = _qkv(rng, (3, 32, 1, 8))
    out = jax.jit(ring_attention(mesh, causal=True, impl="jnp"))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=3e-5
    )


def test_decoder_ring_equals_local(rng, mesh):
    """TinyDecoder forward with mesh ring attention == with local flash
    attention, same params (the module-never-knows-about-meshes contract)."""
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 64)), jnp.int32)

    local = TinyDecoder(vocab=64, d_model=32, n_heads=2, n_layers=2,
                        max_len=128, attn_fn=default_attn(use_flash=False))
    flat = flatten_module(local, jax.random.PRNGKey(0), tokens)

    ringed = TinyDecoder(vocab=64, d_model=32, n_heads=2, n_layers=2,
                         max_len=128,
                         attn_fn=ring_attention(mesh, causal=True, impl="jnp"))

    out_local = flat.apply_flat(flat.w0, tokens)
    out_ring = jax.jit(
        lambda w, t: ringed.apply({"params": flat.unravel(w)}, t)
    )(flat.w0, tokens)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_local), atol=1e-4
    )


@pytest.mark.slow
def test_decoder_trains_with_ring(rng, mesh):
    """A few LM steps through ring attention reduce next-token loss."""
    tokens = jnp.asarray(rng.integers(0, 32, size=(4, 32)), jnp.int32)
    model = TinyDecoder(vocab=32, d_model=16, n_heads=2, n_layers=1,
                        max_len=64,
                        attn_fn=ring_attention(mesh, causal=True, impl="jnp"))
    flat = flatten_module(model, jax.random.PRNGKey(1), tokens)

    def loss_fn(w):
        logp = flat.apply_flat(w, tokens)
        tgt = tokens[:, 1:]
        return -jnp.mean(
            jnp.take_along_axis(logp[:, :-1], tgt[:, :, None], -1)
        )

    vg = jax.jit(jax.value_and_grad(loss_fn))
    w = flat.w0
    l0, _ = vg(w)
    for _ in range(20):
        loss, g = vg(w)
        w = w - 0.5 * g
    assert float(loss) < float(l0) - 0.1, (float(l0), float(loss))


class TestZigzag:
    """Load-balanced causal layout: still exactly full attention."""

    def test_permute_roundtrip(self, rng, mesh):
        from mpit_tpu.parallel.ring_attention import (
            zigzag_permute, zigzag_unpermute,
        )

        x = jnp.asarray(rng.normal(size=(2, 64, 3)), jnp.float32)
        z = zigzag_permute(x, 8)
        assert z.shape == x.shape
        np.testing.assert_array_equal(
            np.asarray(zigzag_unpermute(z, 8)), np.asarray(x)
        )
        # Device 0's first half-chunk is global chunk 0; second is chunk 15.
        c = 64 // 16
        np.testing.assert_array_equal(np.asarray(z[:, :c]), np.asarray(x[:, :c]))
        np.testing.assert_array_equal(
            np.asarray(z[:, c:2 * c]), np.asarray(x[:, 15 * c:])
        )

    @pytest.mark.parametrize("impl", ["jnp", "pallas"])
    def test_matches_full(self, rng, mesh, impl):
        q, k, v = _qkv(rng)  # L=64 = 2*8*4
        ring = ring_attention(mesh, causal=True, impl=impl, layout="zigzag")
        out = jax.jit(ring)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=3e-5
        )

    def test_grads_match_full(self, rng, mesh):
        q, k, v = _qkv(rng)
        ring = ring_attention(mesh, causal=True, impl="jnp", layout="zigzag")
        g1 = jax.jit(jax.grad(lambda q: jnp.sum(ring(q, k, v) ** 2)))(q)
        g2 = jax.grad(lambda q: jnp.sum(_ref(q, k, v, True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-5)

    def _check_pallas_bwd_ring(self, rng, layout, causal, n, L):
        """Shared body: pallas backward ring ((dk, dv) riding the KV
        rotation, per-pair flash-bwd kernels) against dense-oracle grads
        for all three inputs."""
        from mpit_tpu.utils.platform import default_devices

        mesh = sp_mesh(default_devices()[:n])
        q, k, v = _qkv(rng, (1, L, 1, 16))
        g = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
        ring = ring_attention(
            mesh, causal=causal, impl="pallas", layout=layout,
            block_q=8, block_k=128, interpret=True,
        )
        o1, vjp1 = jax.vjp(lambda q, k, v: ring(q, k, v), q, k, v)
        o2, vjp2 = jax.vjp(lambda q, k, v: _ref(q, k, v, causal), q, k, v)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=3e-5)
        for a, b, nm in zip(vjp1(g), vjp2(g), "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4,
                err_msg=f"d{nm} layout={layout} causal={causal} n={n}",
            )

    @pytest.mark.parametrize("layout,causal", [
        ("contiguous", False), ("contiguous", True), ("zigzag", True),
    ])
    def test_pallas_bwd_ring_matches_full(self, rng, layout, causal):
        # 2-device ring: every structural element (rotation, the final
        # homing hop, all four zigzag liveness cases) exists at n=2, and
        # interpret-mode pallas per-call cost stays test-suite friendly.
        self._check_pallas_bwd_ring(rng, layout, causal, n=2, L=16)

    @pytest.mark.slow
    @pytest.mark.parametrize("layout,causal", [
        ("contiguous", False), ("contiguous", True), ("zigzag", True),
    ])
    def test_pallas_bwd_ring_matches_full_deep(self, rng, layout, causal):
        # Multi-hop ring: owner arithmetic asymmetries only visible n>2.
        self._check_pallas_bwd_ring(rng, layout, causal, n=4, L=32)

    def test_zigzag_requires_causal(self, mesh):
        with pytest.raises(ValueError, match="causal"):
            ring_attention(mesh, causal=False, layout="zigzag")

    def test_zigzag_rejects_odd_chunk(self, rng, mesh):
        # The pre-permuted (permute_inputs=False) path must fail loudly at
        # trace time on an odd per-device chunk, not silently drop a row.
        n = mesh.shape["sp"]
        L_odd = n * 3  # 3 per device: odd halves
        q, k, v = _qkv(rng, shape=(1, L_odd, 2, 16))
        ring = ring_attention(
            mesh, causal=True, impl="jnp", layout="zigzag",
            permute_inputs=False,
        )
        with pytest.raises(ValueError, match="even per-device chunk"):
            ring(q, k, v)
