"""Tests for config, timers, serialization utilities."""

import numpy as np
import pytest

from mpit_tpu.utils.config import Config
from mpit_tpu.utils.serialize import (
    decode,
    decode_array,
    encode_array,
    encode_object,
)
from mpit_tpu.utils.timers import PhaseTimers


class TestConfig:
    def test_attribute_and_item_access(self):
        cfg = Config(lr=0.01, opt="easgd")
        assert cfg.lr == 0.01
        assert cfg["opt"] == "easgd"

    def test_get_default(self):
        cfg = Config(lr=0.01)
        assert cfg.get("missing", 7) == 7

    def test_merged_precedence(self):
        base = Config(lr=0.01, mom=0.99)
        out = base.merged({"lr": 0.1}, mom=0.5)
        assert out.lr == 0.1 and out.mom == 0.5
        assert base.lr == 0.01  # original untouched

    def test_parse_args_typed(self):
        cfg = Config(lr=0.01, epochs=10, cuda=False, name="sgd")
        out = cfg.parse_args(["--lr", "0.5", "--cuda", "true", "--epochs", "3"])
        assert out.lr == 0.5 and out.cuda is True and out.epochs == 3
        assert out.name == "sgd"

    def test_missing_attribute_raises(self):
        with pytest.raises(AttributeError):
            Config().nope


class TestTimers:
    def test_phase_accumulates(self):
        tm = PhaseTimers()
        with tm.phase("feval"):
            pass
        with tm.phase("feval"):
            pass
        assert tm.count["feval"] == 2
        assert tm.total["feval"] >= 0.0
        assert "feval" in tm.summary()


class TestSerialize:
    def test_array_roundtrip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = decode_array(encode_array(arr))
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == np.float32

    def test_array_into_preallocated(self):
        arr = np.linspace(0, 1, 8, dtype=np.float32)
        out = np.empty_like(arr)
        result = decode_array(encode_array(arr), out=out)
        assert result is out
        np.testing.assert_array_equal(out, arr)

    def test_bfloat16_via_jax(self):
        import jax.numpy as jnp

        arr = jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3)
        out = decode(encode_array(arr))
        np.testing.assert_array_equal(np.asarray(arr, dtype=np.float32),
                                      np.asarray(out, dtype=np.float32))

    def test_object_roundtrip(self):
        obj = {"offset": 3, "size": (5, 2), "name": "shard"}
        assert decode(encode_object(obj)) == obj

    def test_dispatch(self):
        arr = np.ones(4, dtype=np.int32)
        from mpit_tpu.utils.serialize import encode

        np.testing.assert_array_equal(decode(encode(arr)), arr)
        assert decode(encode({"a": 1})) == {"a": 1}


class TestCheckpoint:
    def test_flat_roundtrip(self, tmp_path):
        from mpit_tpu.utils.checkpoint import load_flat, save_flat

        w = np.linspace(-1, 1, 11, dtype=np.float32)
        path = save_flat(tmp_path, w, {"step": 7})
        w2, meta = load_flat(path)
        np.testing.assert_array_equal(w2, w)
        assert meta["step"] == 7
        w3, _ = load_flat(tmp_path / "ckpt_latest.npz")
        np.testing.assert_array_equal(w3, w)

    def test_flat_roundtrip_bfloat16(self, tmp_path):
        # np.savez alone would degrade ml_dtypes arrays to void records;
        # the raw-bytes layout must preserve the extension dtype.
        import ml_dtypes

        from mpit_tpu.utils.checkpoint import load_flat, save_flat

        w = np.arange(9, dtype=ml_dtypes.bfloat16).reshape(3, 3)
        path = save_flat(tmp_path, w, {"step": 1})
        w2, _ = load_flat(path)
        assert w2.dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(
            w2.astype(np.float32), w.astype(np.float32)
        )
