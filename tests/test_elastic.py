"""Elastic gangs (ISSUE 9 / docs/PROTOCOL.md §9) — controller-driven
scale-up/down, graceful preemption, late admission, reader re-route.

The acceptance invariants: membership changes are **bitwise
transparent** (a run that grew, drained-shrank, and absorbed a
preemption ends with exactly the params of a static run — dedup travels
with the shards, so exactly-once holds across every owner change, even
under deterministic drop/dup fault plans and the int8 error-feedback
codec), **bounded** (drains complete or fail loudly; a retired rank
exits as a goodbye), and **observable** (elastic events + gang-size
gauges + membership epoch; retire-vs-crash is a first-class lease
distinction — a retired rank's silence never triggers failover)."""

import os
import signal
import threading
import time
import urllib.request

import numpy as np
import pytest

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import (
    RETIRED,
    FaultPlan,
    FTConfig,
    FaultyTransport,
    LeaseRegistry,
    PreemptionNotice,
)
from mpit_tpu.ps import ParamClient, ParamServer, ReaderClient, tags
from mpit_tpu.shardctl import ShardController
from mpit_tpu.shardctl import migrate as scmigrate

DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})

FAST_FT = FTConfig(op_deadline_s=0.5, max_retries=10,
                   backoff_base_s=0.005, backoff_cap_s=0.02)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# lease semantics: retire vs crash


class TestLeaseRetire:
    def test_retired_is_terminal_and_never_expires(self):
        now = [0.0]
        reg = LeaseRegistry([0, 1], ttl_s=1.0, clock=lambda: now[0])
        for r in (0, 1):
            reg.arm(r, 0, heartbeats=True)
            reg.renew(r, 0)
        reg.retire(1)
        assert reg.state(1) == RETIRED and reg.gone(1)
        now[0] += 100.0
        # only the crash (rank 0) reads expired; the goodbye never does
        assert reg.expired() == [0]

    def test_admit_registers_for_stop_protocol(self):
        reg = LeaseRegistry([0])
        reg.stop(0)
        assert reg.all_done()
        reg.admit(5)
        assert not reg.all_done()
        reg.stop(5)
        assert reg.all_done()

    def test_retired_counts_as_done(self):
        reg = LeaseRegistry([0, 1])
        reg.stop(0)
        reg.retire(1)
        assert reg.all_done()


# ---------------------------------------------------------------------------
# gang harness: servers + controller threads, spawner for joiners


def launch_elastic(nservers, nclients, nspares=1, ckpt_dir=None, codec=None,
                   client_plans=None, client_ft=FAST_FT, server_ft=FAST_FT,
                   shards_per_server=2, grace_s=5.0, late_clients=0,
                   ctl_kwargs=None):
    """Elastic shardctl topology over the in-process router: rank space
    is provisioned for spares and late clients up front (membership has
    a rank-space ceiling), but spares spawn only via the controller's
    spawner hook and late clients only when the test starts them."""
    n = nservers + nclients + nspares + late_clients + 1
    router = LocalRouter(n)
    sranks = list(range(nservers))
    cranks = list(range(nservers, nservers + nclients))
    late_ranks = list(range(nservers + nclients,
                            nservers + nclients + late_clients))
    ctl_rank = n - 1
    spares = list(range(nservers + nclients + late_clients, ctl_rank))
    servers, threads, notices = {}, {}, {}

    def make_server(r, joiner):
        notices[r] = PreemptionNotice(grace_s=grace_s)
        # Launch members know only the launch clients (late ranks are
        # admission candidates); a joiner spawns after any admissions,
        # so it treats the whole provisioned client space as members.
        servers[r] = ParamServer(
            r, cranks + late_ranks if joiner else list(cranks),
            router.endpoint(r), rule="add",
            ft=server_ft, controller_rank=ctl_rank, ckpt_dir=ckpt_dir,
            ckpt_interval=1e9, shardctl=joiner, preempt=notices[r],
            admit_ranks=late_ranks if not joiner else None)
        threads[r] = threading.Thread(target=servers[r].start, daemon=True)
        threads[r].start()

    for r in sranks:
        make_server(r, joiner=False)
    ctl = ShardController(
        ctl_rank, router.endpoint(ctl_rank), sranks, cranks + late_ranks,
        spawner=lambda r: make_server(r, joiner=True), spare_ranks=spares,
        **(ctl_kwargs or {}))
    clients = []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        clients.append(ParamClient(
            r, sranks, ep, seed_servers=(r == cranks[0]), codec=codec,
            ft=client_ft, shardctl=True, controller_rank=ctl_rank,
            sc_shards_per_server=shards_per_server))
    return dict(router=router, servers=servers, threads=threads,
                notices=notices, ctl=ctl, clients=clients, sranks=sranks,
                cranks=cranks, late_ranks=late_ranks, spares=spares)


def start_clients(clients, w0):
    starters = []
    for i, c in enumerate(clients):
        p = w0.copy() if i == 0 else np.zeros_like(w0)
        starters.append(threading.Thread(
            target=c.start, args=(p, np.zeros_like(w0)), daemon=True))
        starters[-1].start()
    join_all(starters)


def finish(gang):
    clients, ctl = gang["clients"], gang["ctl"]
    clients[0].async_recv_param()
    clients[0].wait()
    out = clients[0].param.copy()
    for c in clients:
        c.stop()
    join_all(list(gang["threads"].values()))
    ctl.pump()
    assert ctl.done, "controller missed client STOPs"
    return out


def run_gang(w0, gtab, rounds, hook=None, **kw):
    gang = launch_elastic(2, 2, **kw)
    start_clients(gang["clients"], w0)
    gang["ctl"].pump()
    assert gang["ctl"].smap is not None
    for r in range(rounds):
        if hook is not None:
            hook(r, gang)
        for i, c in enumerate(gang["clients"]):
            c.grad[:] = gtab[i, r]
            c.async_send_grad()
            c.wait()
    out = finish(gang)
    return out, gang


def tables(size=64, rounds=8, nclients=2, seed=11):
    rng = np.random.default_rng(seed)
    w0 = rng.normal(size=size).astype(np.float32)
    gtab = rng.normal(size=(nclients, rounds, size)).astype(np.float32)
    return w0, gtab


def wait_for(cond, what, timeout=20.0, tick=None):
    t0 = time.monotonic()
    while not cond():
        if tick is not None:
            tick()
        assert time.monotonic() - t0 < timeout, what
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# scale events: bitwise transparency


class TestScaleEvents:
    def test_scale_down_drain_is_bitwise(self):
        """Drain-and-retire a server mid-run: final params bitwise equal
        to the static run; the retired rank exits cleanly as a goodbye,
        not a crash, and clients drop it from their stop fan-out."""
        w0, gtab = tables()
        static, _ = run_gang(w0, gtab, 8)

        def hook(r, gang):
            if r == 4:
                assert gang["ctl"].scale_down(0)
                gang["threads"][0].join(10)
                assert not gang["threads"][0].is_alive(), \
                    "retired server did not exit"
                assert gang["servers"][0].retired

        drained, gang = run_gang(w0, gtab, 8, hook=hook)
        np.testing.assert_array_equal(static, drained)
        assert gang["ctl"].retired == {0}
        assert gang["ctl"].leases.state(0) == RETIRED
        assert all(0 in c._sc_retired for c in gang["clients"]), \
            "clients never learned the retirement broadcast"
        assert gang["servers"][1].owned_shards == [0, 1, 2, 3]

    def test_scale_down_under_faults_and_int8_stays_bitwise(self):
        """The acceptance matrix: drop/dup plans on client data tags plus
        the int8 error-feedback codec, a drain mid-run — still bitwise
        (the residual telescope and per-shard dedup survive the owner
        changes)."""
        w0, gtab = tables(size=4096)
        static, _ = run_gang(w0, gtab, 8, codec="int8")

        def hook(r, gang):
            if r == 3:
                assert gang["ctl"].scale_down(1)

        plans = {i: FaultPlan(seed=i, drop_every=3, dup_every=4,
                              tags=DATA_TAGS) for i in range(2)}
        faulty, gang = run_gang(w0, gtab, 8, codec="int8", hook=hook,
                                client_plans=plans)
        np.testing.assert_array_equal(static, faulty)
        assert sum(int(s.dup_ops) for s in gang["servers"].values()) > 0, \
            "no duplicate was ever admitted — the plan never bit"
        assert any(c.residual_norm() > 0 for c in gang["clients"])

    def test_scale_up_widens_and_scale_down_shrinks(self):
        """Grow onto a spawned joiner (shards migrate to it, clients
        greet it lazily), then drain it again — bitwise, with membership
        epoch and gauges tracking every change."""
        w0, gtab = tables()
        static, _ = run_gang(w0, gtab, 8)
        seen = {}

        def hook(r, gang):
            ctl = gang["ctl"]
            if r == 2:
                new = ctl.scale_up()
                seen["joiner"] = new
                assert len(ctl.smap.shards_of(new)) >= 1, \
                    "scale-up left the joiner shardless"
            if r == 6:
                assert ctl.scale_down(seen["joiner"])
                gang["threads"][seen["joiner"]].join(10)
                assert not gang["threads"][seen["joiner"]].is_alive()

        grown, gang = run_gang(w0, gtab, 8, hook=hook)
        np.testing.assert_array_equal(static, grown)
        ctl = gang["ctl"]
        assert ctl.membership_epoch == 2
        assert int(ctl._m_up.value) == 1 and int(ctl._m_down.value) == 1
        # the joiner was greeted by at least one client mid-run
        assert any(seen["joiner"] in c._sc_greeted
                   for c in gang["clients"])
        assert int(ctl._m_gang_srv.value) == 2  # back to two live servers

    def test_retired_rank_never_fails_over(self, tmp_path):
        """Retire-vs-crash: after a drain-and-retire, the retired rank's
        lease silence must NOT look like a death — no failover, no map
        churn (the goodbye already moved everything)."""
        now = [0.0]
        w0, gtab = tables()

        def hook(r, gang):
            ctl = gang["ctl"]
            now[0] += 1.0
            if r == 3:
                # Arm the lease with a real beat first, then retire.
                wait_for(lambda: ctl.leases.armed(0), "no beat arrived",
                         tick=ctl.pump)
                assert ctl.scale_down(0)
                version = ctl.smap.version
                failovers = int(ctl._m_fail.value)
                now[0] += 1000.0  # far past any TTL
                ctl.check_leases()
                assert int(ctl._m_fail.value) == failovers, \
                    "a retired rank was failed over"
                assert ctl.smap.version == version

        out, gang = run_gang(
            w0, gtab, 8, hook=hook, ckpt_dir=str(tmp_path),
            ctl_kwargs=dict(lease_ttl_s=5.0, clock=lambda: now[0]))
        static, _ = run_gang(w0, gtab, 8)
        np.testing.assert_array_equal(static, out)


# ---------------------------------------------------------------------------
# graceful preemption


class TestPreemption:
    def test_sigterm_handler_sets_flag_only(self):
        """The real signal: SIGTERM to self sets the notice flag (the
        handler's only act — MT-P204); grace accounting happens on the
        observing thread."""
        notice = PreemptionNotice(grace_s=2.0).install()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            wait_for(lambda: notice.notified, "handler never fired",
                     timeout=5)
            assert notice.poll()
            assert 0.0 <= notice.grace_remaining_s() <= 2.0
        finally:
            notice.restore()

    def test_preemption_notice_checkpoints_then_drains(self, tmp_path):
        """Notice -> checkpoint-on-notice (fresh: covers every applied
        grad) -> PREEMPT report -> controller drains gracefully ->
        retire.  Bitwise vs static, and the checkpoint on disk is
        stamped with the exact pre-notice apply count."""
        w0, gtab = tables()
        static, _ = run_gang(w0, gtab, 8)
        state = {}

        def hook(r, gang):
            ctl = gang["ctl"]
            if r == 4:
                victim = gang["servers"][1]
                applied_before = victim.grads_applied
                gang["notices"][1]._notified = True  # the handler's act
                wait_for(lambda: 1 in ctl.retired, "drain never happened",
                         tick=ctl.pump)
                gang["threads"][1].join(10)
                assert not gang["threads"][1].is_alive()
                state["applied"] = applied_before
                assert victim.ckpts_written >= 1, \
                    "checkpoint-on-notice never wrote"

        out, gang = run_gang(w0, gtab, 8, hook=hook,
                             ckpt_dir=str(tmp_path))
        np.testing.assert_array_equal(static, out)
        ctl = gang["ctl"]
        assert int(ctl._m_pre.value) == 1 and int(ctl._m_down.value) == 1
        assert gang["servers"][1].retired
        # Checkpoint freshness: the per-shard snapshots on disk carry
        # every apply the victim had done when the notice landed.
        ckpt_applied = sum(
            scmigrate.load_shard_state(str(tmp_path), sid).grads_applied
            for sid in (2, 3))  # server 1's boot-cut shards
        assert ckpt_applied >= state["applied"]

    def test_stingy_grace_skips_drain(self, tmp_path):
        """A notice under the drain threshold is recorded (events) but
        NOT drained — covering it is failover's job (replay from the
        checkpoint the notice just wrote)."""
        w0, gtab = tables()

        def hook(r, gang):
            ctl = gang["ctl"]
            if r == 4:
                gang["notices"][1]._notified = True
                wait_for(lambda: int(ctl._m_pre.value) == 1,
                         "notice never reached the controller",
                         tick=ctl.pump)
                assert 1 not in ctl.retired

        out, gang = run_gang(
            w0, gtab, 8, hook=hook, ckpt_dir=str(tmp_path), grace_s=0.05,
            ctl_kwargs=dict(preempt_drain_min_s=0.5))
        # grace too small for a drain: the victim kept serving (this
        # in-process harness never actually kills it), so the run is
        # still bitwise and the victim is still live at the end.
        static, _ = run_gang(w0, gtab, 8)
        np.testing.assert_array_equal(static, out)
        assert int(gang["ctl"]._m_down.value) == 0
        assert gang["servers"][1].ckpts_written >= 1


# ---------------------------------------------------------------------------
# late-client admission


class TestLateAdmission:
    def test_late_client_joins_mid_run(self):
        """A client outside the launch-time set announces mid-run
        (INIT v4 through the admission listener), trains alongside the
        original clients, and participates in the stop protocol — no
        gang restart."""
        w0, gtab = tables(rounds=6)
        gang = launch_elastic(2, 2, late_clients=1)
        start_clients(gang["clients"], w0)
        gang["ctl"].pump()
        late_rank = gang["late_ranks"][0]
        extra = np.ones((3, len(w0)), np.float32) * 0.5
        late = None
        for r in range(6):
            if r == 2:
                late = ParamClient(
                    late_rank, gang["sranks"],
                    gang["router"].endpoint(late_rank), ft=FAST_FT,
                    shardctl=True,
                    controller_rank=gang["ctl"].rank,
                    sc_shards_per_server=2)
                t = threading.Thread(
                    target=late.start,
                    args=(np.zeros_like(w0), np.zeros_like(w0)),
                    daemon=True)
                t.start()
                join_all([t])
                gang["clients"].append(late)
            for i, c in enumerate(gang["clients"]):
                if c is late:
                    grad = extra[min(r - 2, 2)] if r - 2 < 3 else None
                    if r - 2 >= 3:
                        continue
                    c.grad[:] = grad
                else:
                    c.grad[:] = gtab[i, r]
                c.async_send_grad()
                c.wait()
        out = finish(gang)
        want = w0 + gtab[:, :6].sum(axis=(0, 1)) + extra.sum(axis=0)
        np.testing.assert_allclose(out, want, rtol=1e-4)
        admits = sum(int(s._m_admits.value)
                     for s in gang["servers"].values())
        assert admits == len(gang["servers"]), \
            "every server should admit the late client exactly once"


# ---------------------------------------------------------------------------
# serving tier: reader re-route on retirement


class TestReaderRetirement:
    def test_goodbye_reroutes_reader_to_successor(self):
        """Read-replica pair: both servers hold the full vector; the
        reader attaches to server 0.  Retirement answers reads with
        GOODBYE(successor=1); the reader re-attaches and keeps reading
        — no RetryExhausted, retry budget untouched."""
        n = 16
        router = LocalRouter(5)  # 0,1 servers; 2,3 writers; 4 reader
        ft = FAST_FT
        servers = [
            ParamServer(0, [2], router.endpoint(0), rule="add", ft=ft,
                        reader_ranks=[4]),
            ParamServer(1, [3], router.endpoint(1), rule="add", ft=ft,
                        reader_ranks=[4]),
        ]
        threads = [threading.Thread(target=s.start, daemon=True)
                   for s in servers]
        for t in threads:
            t.start()
        w = np.arange(n, dtype=np.float32)
        writers = [
            ParamClient(2, [0], router.endpoint(2), seed_servers=True,
                        ft=ft),
            ParamClient(3, [1], router.endpoint(3), seed_servers=True,
                        ft=ft),
        ]
        starters = []
        for wr in writers:
            starters.append(threading.Thread(
                target=wr.start, args=(w.copy(), np.zeros_like(w)),
                daemon=True))
            starters[-1].start()
        join_all(starters)
        reader = ReaderClient(4, [0], router.endpoint(4), ft=ft)
        mirror = np.zeros(n, np.float32)
        reader.start(mirror)
        reader.read_params()
        np.testing.assert_array_equal(mirror, w)
        # retire server 0's serving slot toward its replica
        servers[0].retire_serving(successor=1)
        mirror[:] = 0
        reader.read_params()  # GOODBYE -> re-attach at 1 -> served
        np.testing.assert_array_equal(mirror, w)
        assert int(reader._m_reroutes.value) == 1
        assert reader._route == {0: 1}
        mirror[:] = 0
        reader.read_params()  # subsequent reads go straight to 1
        np.testing.assert_array_equal(mirror, w)
        assert int(reader._m_reroutes.value) == 1
        reader.stop()
        for wr in writers:
            wr.stop()
        join_all(threads)


# ---------------------------------------------------------------------------
# operator-driven scaling: the statusd /scale route


class TestScaleRoute:
    def test_scale_route_queues_and_pump_executes(self):
        """GET /scale?op=down&rank=0 on the controller's endpoint queues
        the request (HTTP thread) and pump() executes it (control
        thread) — the wiring an operator uses mid-run."""
        from mpit_tpu.obs import statusd

        w0, gtab = tables()
        gang = launch_elastic(2, 2)
        start_clients(gang["clients"], w0)
        ctl = gang["ctl"]
        ctl.pump()
        server = statusd.StatusServer(0)  # ephemeral port
        statusd.register_action("scale", ctl._scale_action)
        try:
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(f"{base}/scale?op=down&rank=0",
                                        timeout=5) as resp:
                import json

                body = json.loads(resp.read())
            assert body["queued"] == {"op": "down", "rank": "0"}
            with urllib.request.urlopen(f"{base}/scale?op=sideways",
                                        timeout=5) as resp:
                assert b"error" in resp.read()
            for r in range(4):
                for i, c in enumerate(gang["clients"]):
                    c.grad[:] = gtab[i, r]
                    c.async_send_grad()
                    c.wait()
                ctl.pump()
            wait_for(lambda: 0 in ctl.retired, "queued scale-down never ran",
                     tick=ctl.pump)
            finish(gang)
        finally:
            statusd.clear_providers()
            server.close()


# ---------------------------------------------------------------------------
# the fast chaos soak: >= 3 membership changes, bitwise, bounded


class TestChaosSoak:
    def test_soak_grow_shrink_preempt_is_bitwise(self, tmp_path):
        """The §9 proof in miniature: mid-DOWNPOUR-shaped lockstep the
        gang (a) grows onto a spawned joiner, (b) drain-shrinks an
        original server, (c) absorbs a graceful preemption of another —
        three membership changes, ending on a gang whose only server is
        the mid-run joiner.  Final params bitwise-equal the static run
        (exactly-once held across every owner change) and every stage
        completed inside its bound (no hang)."""
        w0, gtab = tables(rounds=10, seed=23)
        static, _ = run_gang(w0, gtab, 10)
        joiner = {}

        def hook(r, gang):
            ctl = gang["ctl"]
            if r == 2:
                joiner["rank"] = ctl.scale_up()
            if r == 5:
                assert ctl.scale_down(0)
                gang["threads"][0].join(10)
                assert not gang["threads"][0].is_alive()
            if r == 8:
                gang["notices"][1]._notified = True
                wait_for(lambda: 1 in ctl.retired, "preempt drain hung",
                         tick=ctl.pump)
                gang["threads"][1].join(10)
                assert not gang["threads"][1].is_alive()

        out, gang = run_gang(w0, gtab, 10, hook=hook,
                             ckpt_dir=str(tmp_path))
        np.testing.assert_array_equal(static, out)
        ctl = gang["ctl"]
        events = {"up": int(ctl._m_up.value),
                  "down": int(ctl._m_down.value),
                  "preempt": int(ctl._m_pre.value)}
        assert events == {"up": 1, "down": 2, "preempt": 1}
        assert ctl.membership_epoch == 3
        # the whole vector ended up on the joiner
        assert gang["servers"][joiner["rank"]].owned_shards == [0, 1, 2, 3]
        assert int(ctl._m_gang_srv.value) == 1


# ---------------------------------------------------------------------------
# the slow soak: real processes, launch --elastic, SIGTERM-grace chaos


@pytest.mark.slow
def test_launch_elastic_preemption_soak(tmp_path, monkeypatch):
    """np=5 (2s/2c/1ctl) + 1 spare DOWNPOUR gang over TCP via
    ``--elastic``: the supervisor SIGTERMs server rank 2 mid-run with a
    grace window (spot-style preemption).  The rank checkpoints on
    notice, reports PREEMPT, the controller drains it through live
    migration, marks it retired in the scale mailbox (so the supervisor
    never respawns it), and the run converges in the fault-free
    envelope on the surviving membership."""
    import socket

    from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

    socks = [socket.socket() for _ in range(6)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    addrs = ",".join(f"127.0.0.1:{s.getsockname()[1]}" for s in socks)
    for s in socks:
        s.close()
    monkeypatch.setenv("MPIT_TCP_RECONNECT_S", "60")
    cfg = LAUNCH_DEFAULTS.merged(
        # epochs sized so the +90s preemption lands mid-training: on a
        # 1-core box five children importing jax serialize to ~45s of
        # boot before any role (or SIGTERM handler) exists, and
        # training then runs ~0.15s/epoch.
        np=5, opt="downpour", lr=0.2, su=1, epochs=1000, batch=64, side=8,
        master_freq=2, device_policy="cpu", transport="tcp",
        tcp_addrs=addrs,
        ft_heartbeat_s=0.25, ft_lease_ttl_s=30.0, ft_op_deadline_s=5.0,
        supervise=2,
        server_ckpt_dir=str(tmp_path), server_ckpt_interval=2.0,
        elastic=True, elastic_spares=1, elastic_grace_s=25.0,
        elastic_shards_per_server=2,
        shardctl_lease_ttl_s=30.0,
    )
    # Chaos arm: preempt (SIGTERM + grace) server rank 2 mid-run.  The
    # supervisor escalates to SIGKILL only if the drain overruns.
    import mpit_tpu.ft.supervisor as sup

    orig = sup.supervise_gang

    def with_chaos(*args, **kw):
        kw.update(chaos_kill_rank=2, chaos_kill_after_s=90.0,
                  chaos_signal=signal.SIGTERM, chaos_grace_s=25.0)
        return orig(*args, **kw)

    monkeypatch.setattr(sup, "supervise_gang", with_chaos)
    results = launch_processes(cfg, timeout=600)
    roles = {r: v["role"] for r, v in results.items()}
    assert roles[4] == "controller"
    assert roles[1] == roles[3] == "worker"
    ctl = results[4]
    assert ctl["elastic_events"]["preempt"] >= 1, ctl
    assert ctl["elastic_events"]["down"] >= 1, ctl
    workers = [v for v in results.values() if v["role"] == "worker"]
    assert all(w["final_test_err"] < 0.8 for w in workers)
