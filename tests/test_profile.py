"""CPU/utilization attribution plane (obs/profile.py).

Four layers of assertion:

1. the profiler primitive: step attribution (negative deltas clamped,
   never negative totals), throttled counter-track sampling, the
   metric bindings (``mpit_sched_cpu_seconds_total`` /
   ``mpit_sched_runq``), and the enablement contract — profiling is
   OFF even when obs is on, and the disabled object is the shared
   null singleton;
2. scheduler integration: a CPU-burning task run under profiling
   carries ``cpu_s`` on the Task, ``cpu_us`` on its recorded
   lifecycle, and an attribution row in the profiler;
3. deterministic counter-track round trips: samples written by the
   trace exporter validate as ``ph:"C"`` events, survive a merge with
   per-rank (pid) tracks kept distinct, and surface in
   ``analyze_trace``;
4. the offline report: cpu attribution is non-negative and
   sums-to-wall by construction (clamping both directions), and the
   ``profile`` CLI round-trips --json / --require-counters, while
   flight dumps for ``scheduler_stall`` carry a well-formed resources
   section (validate_dump enforces the shape).
"""

import json

import pytest

from mpit_tpu import obs
from mpit_tpu.aio import Scheduler
from mpit_tpu.obs import causal as obs_causal
from mpit_tpu.obs import flight as obs_flight
from mpit_tpu.obs import metrics as obs_metrics
from mpit_tpu.obs import profile as obs_profile
from mpit_tpu.obs import spans as obs_spans
from mpit_tpu.obs import trace as obs_trace
from mpit_tpu.obs.__main__ import main as obs_cli


@pytest.fixture
def prof_on():
    """obs + profiling forced on, everything reset on the way out.
    Order matters: obs.configure(reset=True) clears the profile
    override too, so the profile flip comes second."""
    obs.configure(enabled=True, reset=True)
    obs_profile.configure(enabled=True, reset=True)
    try:
        yield obs_profile.get_profiler()
    finally:
        obs.configure(enabled=None, reset=True)


def burn_task(rounds=40, width=4000):
    """A generator task that does real arithmetic per step — enough
    thread-time to stamp, few enough steps to stay fast."""
    acc = 0
    for _ in range(rounds):
        acc += sum(i * i for i in range(width))
        yield
    return acc


# ---------------------------------------------------------------------------
# the profiler primitive + enablement


class TestProfilerPrimitive:
    def test_profiling_off_even_when_obs_on(self):
        obs.configure(enabled=True, reset=True)
        try:
            assert obs.obs_enabled()
            assert not obs_profile.profile_enabled()
            assert obs_profile.get_profiler() is obs_profile.NULL_PROFILER
        finally:
            obs.configure(enabled=None, reset=True)

    def test_env_enablement_implies_obs(self, monkeypatch):
        monkeypatch.setenv(obs_profile.PROFILE_ENV, "1")
        # MPIT_OBS_PROFILE alone turns obs on (like a trace request)
        assert obs_metrics.obs_enabled()
        assert obs_profile.profile_enabled()
        monkeypatch.setenv(obs_profile.PROFILE_ENV, "0")
        assert not obs_profile.profile_enabled()

    def test_step_attributes_and_counts(self, prof_on):
        prof = prof_on
        prof.step("apply", 0.010)
        prof.step("apply", 0.005)
        prof.step("encode", 0.002)
        prof.step("noise", -0.5)  # foreign-thread stamp: dropped
        prof.step("noise", 0.0)
        assert prof.task_cpu["apply"] == pytest.approx(0.015)
        assert "noise" not in prof.task_cpu
        assert prof.cpu_seconds == pytest.approx(0.017)
        reg = obs.get_registry()
        c = reg.counter("mpit_sched_cpu_seconds_total")
        assert c.value == pytest.approx(0.017)
        top = prof.top_tasks(1)
        assert top == [["apply", pytest.approx(15000.0)]]

    def test_sample_emits_tracks_and_throttles(self, prof_on):
        prof = prof_on
        prof._interval = 0.0  # deterministic: no rate cap
        prof.step("t", 0.001)
        prof.sample(3)
        tracks = {track for _, track, _ in prof.samples}
        # no pool in this process path — the scheduler tracks only
        assert {"sched_runq", "task_cpu"} <= tracks
        assert prof.last_runq == 3
        g = obs.get_registry().gauge("mpit_sched_runq")
        assert g.value == 3
        # throttle: a huge interval means the next call is a no-op
        n = len(prof.samples)
        prof._interval = 3600.0
        prof.sample(9)
        assert len(prof.samples) == n and prof.last_runq == 3

    def test_cpu_now_is_a_real_clock(self, prof_on):
        t0 = prof_on.cpu_now()
        sum(i * i for i in range(50_000))
        assert prof_on.cpu_now() >= t0

    def test_resource_snapshot_sections(self, prof_on):
        prof_on.step("hot", 0.004)
        prof_on._interval = 0.0
        prof_on.sample(2)
        snap = obs_profile.resource_snapshot()
        assert snap["sched"] == {"runq": 2,
                                 "cpu_seconds": pytest.approx(0.004)}
        assert ["hot", pytest.approx(4000.0)] in snap["top_tasks"]
        obs.configure(enabled=None, reset=True)
        # disabled: no sched/top sections (pool may exist from other
        # tests — pool-only is legal, so only assert the absence)
        snap = obs_profile.resource_snapshot()
        assert "sched" not in snap and "top_tasks" not in snap


# ---------------------------------------------------------------------------
# scheduler integration


class TestSchedulerStamping:
    def test_tasks_carry_cpu(self, prof_on):
        prof = prof_on
        prof._interval = 0.0
        sched = Scheduler(idle_usec=0)
        sched.spawn(burn_task(), name="burn")
        sched.wait()
        assert prof.task_cpu.get("burn", 0.0) > 0.0
        assert prof.cpu_seconds > 0.0
        rec = obs_spans.get_recorder()
        rows = {name: cpu for name, _, _, _, cpu in rec.tasks}
        assert rows["burn"] > 0.0
        # the ping pass sampled the run queue at least once
        assert any(track == "sched_runq" for _, track, _ in prof.samples)

    def test_disabled_scheduler_stamps_nothing(self):
        obs.configure(enabled=True, reset=True)  # obs on, profiling off
        try:
            sched = Scheduler(idle_usec=0)
            sched.spawn(burn_task(rounds=3), name="burn")
            sched.wait()
            rec = obs_spans.get_recorder()
            rows = {name: cpu for name, _, _, _, cpu in rec.tasks}
            assert rows["burn"] == 0.0
        finally:
            obs.configure(enabled=None, reset=True)


# ---------------------------------------------------------------------------
# counter-track round trips


def _sampled_trace(tmp_path, prof, rank, n=4):
    """Write one rank's trace after n deterministic samples."""
    prof._interval = 0.0
    for i in range(n):
        prof.step(f"task{rank}", 0.001)
        prof.sample(i)
    path = str(tmp_path / f"trace.rank{rank}.json")
    obs_trace.write_rank_trace(path, rank=rank, role="server")
    return path


class TestCounterTracks:
    def test_round_trip_validates(self, prof_on, tmp_path):
        path = _sampled_trace(tmp_path, prof_on, rank=0)
        stats = obs_trace.validate_trace(path)
        assert stats["counters"] >= 8  # 2 tracks x 4 samples
        with open(path) as fh:
            events = json.load(fh)["traceEvents"]
        cs = [ev for ev in events if ev.get("ph") == "C"]
        assert cs and all(ev["cat"] == "resource" and ev["tid"] == 0
                          and isinstance(ev["args"]["value"], (int, float))
                          for ev in cs)
        assert {ev["name"] for ev in cs} == {"sched_runq", "task_cpu"}

    def test_malformed_counter_rejected(self, prof_on, tmp_path):
        path = _sampled_trace(tmp_path, prof_on, rank=0)
        with open(path) as fh:
            obj = json.load(fh)
        for ev in obj["traceEvents"]:
            if ev.get("ph") == "C":
                ev["args"] = {}  # strip the value
                break
        with pytest.raises(ValueError, match="without numeric args.value"):
            obs_trace.validate_trace(obj)

    def test_merge_keeps_per_rank_tracks_distinct(self, prof_on, tmp_path):
        p0 = _sampled_trace(tmp_path, prof_on, rank=0)
        p1 = _sampled_trace(tmp_path, prof_on, rank=1)
        merged = str(tmp_path / "trace.json")
        obs_trace.merge_traces(merged, [p0, p1])
        assert obs_trace.validate_trace(merged)["counters"] > 0
        with open(merged) as fh:
            events = json.load(fh)["traceEvents"]
        by_pid = {}
        for ev in events:
            if ev.get("ph") == "C":
                by_pid.setdefault(ev["pid"], set()).add(ev["name"])
        # counters are keyed per pid: both ranks keep their own tracks
        assert set(by_pid) == {0, 1}
        assert all("sched_runq" in tracks for tracks in by_pid.values())
        report = obs_profile.analyze_trace(merged)
        assert report["counter_events"] > 0
        assert report["ranks"]["0"]["counter_samples"]["task_cpu"] >= 4


# ---------------------------------------------------------------------------
# cpu attribution math (non-negative, sums-to-wall by construction)


def _synthetic_span_events(cpu_encode, cpu_span):
    """One client GRAD span: 100us encode phase + 300us total wall,
    with the given cpu riders (possibly out of range — the clamp is
    the thing under test)."""
    return [
        {"ph": "B", "cat": "ps_op", "name": "GRAD", "pid": 0, "tid": 1,
         "ts": 1000.0, "args": {"side": "client", "peer": 1}},
        {"ph": "X", "cat": "ps_phase", "name": "GRAD.encode", "pid": 0,
         "tid": 1, "ts": 1000.0, "dur": 100.0,
         "args": {"cpu_us": cpu_encode}},
        {"ph": "E", "cat": "ps_op", "name": "GRAD", "pid": 0, "tid": 1,
         "ts": 1300.0, "args": {"outcome": "ok", "cpu_us": cpu_span}},
    ]


class TestCpuAttribution:
    @pytest.mark.parametrize("cpu_encode,cpu_span", [
        (40.0, 250.0),     # in range
        (500.0, 900.0),    # rider above wall: clamps to wall
        (-30.0, -1.0),     # negative rider: clamps to zero
    ])
    def test_non_negative_and_sums_to_wall(self, cpu_encode, cpu_span):
        spans = obs_causal.extract_spans(
            _synthetic_span_events(cpu_encode, cpu_span))
        attr = obs_causal.cpu_attribution(spans)
        rows = attr["GRAD/client"]
        for row in rows.values():
            assert row["cpu_us"] >= 0.0 and row["off_cpu_us"] >= 0.0
            assert row["cpu_us"] + row["off_cpu_us"] == \
                pytest.approx(row["wall_us"])
        assert rows["encode"]["wall_us"] == pytest.approx(100.0)
        assert rows["encode"]["cpu_us"] == \
            pytest.approx(min(max(cpu_encode, 0.0), 100.0))
        assert rows["(span)"]["wall_us"] == pytest.approx(300.0)
        assert rows["(span)"]["cpu_us"] == \
            pytest.approx(min(max(cpu_span, 0.0), 300.0))

    def test_no_riders_means_none(self):
        events = _synthetic_span_events(10.0, 20.0)
        for ev in events:
            ev.get("args", {}).pop("cpu_us", None)
        spans = obs_causal.extract_spans(events)
        assert obs_causal.cpu_attribution(spans) is None

    def test_analyze_trace_ops_table(self):
        trace = {"traceEvents": _synthetic_span_events(40.0, 250.0),
                 "otherData": {}}
        report = obs_profile.analyze_trace(trace)
        op = report["ops"]["GRAD/client"]
        assert op["count"] == 1
        assert op["cpu_us"] + op["off_cpu_us"] == \
            pytest.approx(op["wall_us"])
        assert report["cpu_phases"]["GRAD/client"]["encode"]["cpu_us"] == \
            pytest.approx(40.0)


# ---------------------------------------------------------------------------
# the profile CLI


class TestProfileCLI:
    def test_report_and_json(self, prof_on, tmp_path, capsys):
        rec = obs_spans.get_recorder()
        sp = rec.op("GRAD", peer=1, side="client", epoch=0)
        sp.mark("encode")
        sp.end("ok")
        path = _sampled_trace(tmp_path, prof_on, rank=0)
        assert obs_cli(["profile", path, "--require-counters"]) == 0
        out = capsys.readouterr().out
        assert "counter sample" in out and "rank 0" in out
        assert obs_cli(["profile", path, "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["counter_events"] >= 8
        assert "GRAD/client" in report["ops"]

    def test_require_counters_gates(self, tmp_path, capsys):
        obs.configure(enabled=True, reset=True)  # profiling OFF
        try:
            path = str(tmp_path / "bare.json")
            obs_trace.write_rank_trace(path, rank=0)
        finally:
            obs.configure(enabled=None, reset=True)
        assert obs_cli(["profile", path]) == 0
        capsys.readouterr()
        assert obs_cli(["profile", path, "--require-counters"]) == 1

    def test_unreadable_trace_is_rc2(self, tmp_path):
        assert obs_cli(["profile", str(tmp_path / "missing.json")]) == 2


# ---------------------------------------------------------------------------
# flight-dump resources section


class TestFlightResources:
    def test_stall_dump_carries_resources(self, prof_on, tmp_path,
                                          monkeypatch):
        monkeypatch.setenv(obs_flight.ENV_DIR, str(tmp_path))
        prof_on.step("stuck", 0.003)
        prof_on._interval = 0.0
        prof_on.sample(1)
        fl = obs_flight.get_flight()
        fl.record("task", name="stuck", state="RUNNING")
        path = fl.dump("scheduler_stall")
        assert obs_flight.validate_dump(path)["reason"] == "scheduler_stall"
        with open(path) as fh:
            obj = json.load(fh)
        assert obj["resources"]["sched"]["runq"] == 1
        assert obj["resources"]["top_tasks"][0][0] == "stuck"

    def test_validator_enforces_shape(self, prof_on, tmp_path, monkeypatch):
        monkeypatch.setenv(obs_flight.ENV_DIR, str(tmp_path))
        path = obs_flight.get_flight().dump("scheduler_stall")
        with open(path) as fh:
            good = json.load(fh)
        bad = dict(good)
        bad.pop("resources")
        with pytest.raises(ValueError, match="no resources section"):
            obs_flight.validate_dump(bad)
        bad = json.loads(json.dumps(good))
        bad["resources"]["pool"] = {"threads": 4}  # missing depth/busy
        with pytest.raises(ValueError, match="resources.pool"):
            obs_flight.validate_dump(bad)
        bad = json.loads(json.dumps(good))
        bad["resources"]["sched"] = {"runq": 0}  # missing cpu_seconds
        with pytest.raises(ValueError, match="resources.sched"):
            obs_flight.validate_dump(bad)
        bad = json.loads(json.dumps(good))
        bad["resources"]["top_tasks"] = [["t"]]  # not a [name, cpu] pair
        with pytest.raises(ValueError, match="top_tasks"):
            obs_flight.validate_dump(bad)
        # other reasons never require the section
        other = json.loads(json.dumps(good))
        other["reason"] = "retry_exhausted"
        other.pop("resources")
        assert obs_flight.validate_dump(other)["reason"] == "retry_exhausted"
