"""mpit_tpu.lm — the flagship LM workload.

Four layers:

- the packed token stream's determinism contract (bitwise-identical
  batches for equal ``(seed, step)`` — across calls, across a fresh
  *process*, and across the supervisor-restart pattern of recreating
  the stream object and resuming mid-run);
- the shard plan (aligned weighted cuts tile the flat vector on
  parameter boundaries; the footprint model prices optimizer slots);
- the static ``layout=`` seam on ParamClient/ReaderClient — the
  weighted cut replaces the equal split and composes with chunked
  streaming and the int8 error-feedback codec;
- the LmTrainer loop (local sgd learns; tokens/sec accounting).
"""

import hashlib
import subprocess
import sys
import threading

import numpy as np
import pytest

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import FTConfig
from mpit_tpu.lm import (
    EOS,
    LmTrainer,
    PackedStream,
    audit_rules,
    build,
    packed_batch,
    plan,
    train_state_tree,
)
from mpit_tpu.ps import ParamClient, ParamServer
from mpit_tpu.ps.serve import ReaderClient
from mpit_tpu.utils.config import Config


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "gang thread did not stop (hang)"


# ---------------------------------------------------------------------------
# packed stream determinism (the data half of bitwise reproducibility)


class TestPackedStream:
    def test_shape_dtype_vocab(self):
        b = packed_batch(3, 0, batch=4, seq_len=32)
        assert b.shape == (4, 33) and b.dtype == np.int32
        assert b.min() >= 0 and b.max() < 256

    def test_eos_separators_present(self):
        # packing concatenates EOS-terminated docs: the grid must
        # contain separators but not be all-EOS
        b = packed_batch(3, 0, batch=4, seq_len=32)
        assert (b == EOS).any()
        assert (b != EOS).sum() > b.size // 2

    def test_bitwise_determinism_in_process(self):
        a = packed_batch(11, 7, batch=8, seq_len=64)
        b = packed_batch(11, 7, batch=8, seq_len=64)
        np.testing.assert_array_equal(a, b)
        assert a.tobytes() == b.tobytes()

    def test_steps_and_seeds_decorrelated(self):
        base = packed_batch(11, 7, batch=8, seq_len=64)
        assert packed_batch(11, 8, batch=8, seq_len=64).tobytes() \
            != base.tobytes()
        assert packed_batch(12, 7, batch=8, seq_len=64).tobytes() \
            != base.tobytes()

    def test_bitwise_determinism_across_processes(self):
        """The cross-process half of the contract: a fresh interpreter
        (fresh numpy, fresh global RNG state) produces the same bytes."""
        prog = (
            "import hashlib\n"
            "from mpit_tpu.lm import packed_batch\n"
            "h = hashlib.sha256()\n"
            "for step in (0, 1, 5):\n"
            "    h.update(packed_batch(11, step, batch=4,"
            " seq_len=32).tobytes())\n"
            "print(h.hexdigest())\n"
        )
        out = subprocess.run([sys.executable, "-c", prog],
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        h = hashlib.sha256()
        for step in (0, 1, 5):
            h.update(packed_batch(11, step, batch=4, seq_len=32).tobytes())
        assert out.stdout.strip() == h.hexdigest()

    def test_restart_resumes_identically(self):
        """Supervisor-restart semantics: a NEW stream object (the dead
        incarnation's state is gone) resumes at step k with exactly the
        batch the old one would have produced — no replay needed."""
        first = PackedStream(5, 4, 32)
        want = [first.batch_at(k).tobytes() for k in range(8)]
        reborn = PackedStream(5, 4, 32)
        got = [reborn.batch_at(k).tobytes() for k in range(4, 8)]
        assert got == want[4:8]

    def test_global_rng_state_untouched(self):
        state = np.random.get_state()[1].copy()
        packed_batch(1, 0, batch=2, seq_len=16)
        np.testing.assert_array_equal(np.random.get_state()[1], state)

    def test_bad_shapes_raise(self):
        with pytest.raises(ValueError):
            packed_batch(0, 0, batch=0, seq_len=32)
        with pytest.raises(ValueError):
            packed_batch(0, 0, batch=2, seq_len=1)


# ---------------------------------------------------------------------------
# the shard plan


class TestLmPlan:
    def _params(self):
        model = build(d_model=16, n_heads=2, n_layers=1, seq_len=16,
                      use_flash=False)
        return model.flat.unravel(model.flat.w0), model.flat.size

    def test_layout_tiles_on_parameter_boundaries(self):
        params, plong = self._params()
        p = plan(params, 3)
        assert p.plong == plong
        boundaries = {s.offset for s in p.segments}
        pos = 0
        for sh in p.layout:
            assert sh.offset == pos and sh.size > 0
            assert sh.offset in boundaries or sh.offset == 0
            pos = sh.end
        assert pos == plong

    def test_weighted_cut_skews_toward_heavy_servers(self):
        # dense parameter boundaries so the weighted target can land
        # near its fraction (the real model's coarse leaves snap harder)
        params = {f"p{i:02d}": np.zeros(64, np.float32) for i in range(16)}
        even = plan(params, 2).layout
        skewed = plan(params, 2, server_weights=[3, 1]).layout
        assert even[0].size == even[1].size == 512
        assert skewed[0].size > even[0].size
        assert skewed[0].size > 2 * skewed[1].size  # 3:1 target, aligned

    def test_footprint_prices_optimizer_slots(self):
        params, plong = self._params()
        p_add = plan(params, 2, rule="add")
        p_adam = plan(params, 2, rule="adam")
        assert p_add.layout == p_adam.layout  # rule never moves the cut
        for i in range(2):
            assert p_add.footprint_bytes(i) == p_add.layout[i].size * 4
            assert p_adam.footprint_bytes(i) == p_add.footprint_bytes(i) * 3
        s = p_adam.summary()
        assert s["servers"] == 2 and s["slots"] == 2
        assert sum(s["shard_elems"]) == plong

    def test_shard_map_lift_is_valid(self):
        params, plong = self._params()
        smap = plan(params, 2).shard_map([0, 2])
        assert smap.plong == plong and smap.version == 0
        assert [e.owner for e in smap.entries] == [0, 2]

    def test_audit_covers_the_train_state(self):
        params, _ = self._params()
        report = audit_rules(train_state_tree(params, "adam"))
        assert report and not any(i == -2 for i in report.values())

    def test_bad_weights_raise(self):
        params, _ = self._params()
        with pytest.raises(ValueError):
            plan(params, 2, server_weights=[1, 2, 3])
        with pytest.raises(ValueError):
            plan(params, 2, server_weights=[1, 0])
        with pytest.raises(ValueError):
            plan(params, 0)


# ---------------------------------------------------------------------------
# the static layout= seam on the PS clients


def _gang_ft(chunk_bytes=0):
    return FTConfig(op_deadline_s=2.0, max_retries=8,
                    backoff_base_s=0.005, backoff_cap_s=0.02,
                    chunk_bytes=chunk_bytes)


class TestClientLayout:
    def _run(self, layout, size, *, codec=None, chunk_bytes=0,
             reader=False):
        """1 client (+ optional reader) against len(layout) servers; the
        client pushes one delta and pulls; returns (servers, param[,
        read])."""
        nserv = len(layout)
        n = nserv + 1 + (1 if reader else 0)
        router = LocalRouter(n)
        ft = _gang_ft(chunk_bytes)
        servers = [
            ParamServer(r, [nserv], router.endpoint(r), ft=ft,
                        reader_ranks=([nserv + 1] if reader else None))
            for r in range(nserv)
        ]
        threads = [threading.Thread(target=s.start, daemon=True)
                   for s in servers]
        for t in threads:
            t.start()
        client = ParamClient(nserv, list(range(nserv)),
                             router.endpoint(nserv), seed_servers=True,
                             codec=codec, ft=ft, layout=layout)
        param = np.arange(size, dtype=np.float32)
        grad = np.zeros(size, np.float32)
        client.start(param, grad)
        grad[:] = 1.0
        client.async_send_grad()
        client.async_recv_param()
        client.wait()
        read = None
        if reader:
            rc = ReaderClient(nserv + 1, list(range(nserv)),
                              router.endpoint(nserv + 1), codec=codec,
                              ft=ft, layout=layout)
            mirror = np.zeros(size, np.float32)
            rc.start(mirror)
            rc.read_params()
            read = mirror.copy()
            rc.stop()
        client.stop()
        for s in servers:
            s.live.stop()
        join_all(threads)
        return servers, param, read

    def test_servers_adopt_the_weighted_cut(self):
        params = {"a": np.zeros((6, 4), np.float32),
                  "b": np.zeros(40, np.float32),
                  "c": np.zeros((8, 2), np.float32)}
        layout = plan(params, 2, server_weights=[3, 1]).layout
        servers, param, _ = self._run(layout, 80)
        # each server holds exactly its planned shard, not the equal split
        for srv, shard in zip(servers, layout):
            assert (srv.offset, srv.size) == (shard.offset, shard.size)
        np.testing.assert_allclose(
            param, np.arange(80, dtype=np.float32) + 1.0, rtol=1e-6)

    def test_layout_composes_with_chunked_int8(self):
        # uneven cut + FLAG_CHUNKED streaming + int8 error feedback: the
        # flagship static composition, down to byte-exact pull of what
        # the servers hold
        params = {"a": np.zeros(96, np.float32),
                  "b": np.zeros((32, 8), np.float32),
                  "c": np.zeros(160, np.float32)}
        layout = plan(params, 2, server_weights=[5, 3]).layout
        servers, param, read = self._run(layout, 512, codec="int8",
                                         chunk_bytes=256, reader=True)
        held = np.concatenate([np.asarray(s.param) for s in servers])
        # writer pull and reader read decode the SAME served bytes ->
        # bitwise agreement; against the f32 shard the error is bounded
        # by the int8 quantization step
        np.testing.assert_array_equal(param, read)
        q = float(np.abs(held).max()) / 127.0
        np.testing.assert_allclose(param, held, atol=2 * q)

    def test_reader_layout_matches_writers(self):
        params = {"a": np.zeros(30, np.float32),
                  "b": np.zeros(34, np.float32)}
        layout = plan(params, 2, server_weights=[2, 1]).layout
        _, param, read = self._run(layout, 64, reader=True)
        np.testing.assert_array_equal(read, param)

    def test_layout_validation_is_loud(self):
        router = LocalRouter(2)
        params = {"a": np.zeros(64, np.float32)}
        layout = plan(params, 1).layout
        with pytest.raises(ValueError, match="exactly one each"):
            ParamClient(1, [0, 2], router.endpoint(1), layout=layout)
        with pytest.raises(ValueError, match="cannot combine"):
            ParamClient(1, [0], router.endpoint(1), layout=layout,
                        shardctl=True)
        with pytest.raises(ValueError, match="exactly one each"):
            ReaderClient(1, [0, 2], router.endpoint(1), layout=layout)
        # registered vector shorter than the layout: caught at start()
        client = ParamClient(1, [0], router.endpoint(1), layout=layout)
        with pytest.raises(ValueError, match="registered vector"):
            client.start(np.zeros(32, np.float32),
                         np.zeros(32, np.float32))


# ---------------------------------------------------------------------------
# the trainer loop


class TestLmTrainer:
    CFG = Config(d_model=32, n_heads=2, n_layers=1, seq_len=32, batch=4,
                 opt="sgd", lr=0.5, steps=30, eval_every=15,
                 eval_batches=1, seed=0, use_flash=0)

    def test_local_sgd_learns(self):
        res = LmTrainer(self.CFG).run()
        losses = [h["avg_loss"] for h in res["history"]]
        assert all(np.isfinite(x) for x in losses)
        # byte stream entropy floor is ln(256) ~ 5.545; training from a
        # random init must descend toward it
        assert losses[-1] < losses[0]
        assert res["final_eval_loss"] < 6.5

    def test_tokens_accounting(self):
        res = LmTrainer(self.CFG).run()
        assert res["tokens_total"] == 30 * 4 * 32
        assert res["tokens_per_s"] > 0
        assert res["train_seconds"] > 0
        # history rows carry the live tokens/sec trajectory
        assert all(h["tokens_per_s"] > 0 for h in res["history"])

    def test_server_opts_require_a_client(self):
        cfg = self.CFG.merged({"opt": "downpour"})
        with pytest.raises(ValueError, match="parameter client"):
            LmTrainer(cfg).run()

    def test_unknown_opt_raises(self):
        cfg = self.CFG.merged({"opt": "nope"})
        with pytest.raises(ValueError, match="unknown optimizer"):
            LmTrainer(cfg).run()
