"""mpit_tpu.dplane — device-resident data plane tests.

Three layers:

- the partition-rule engine's invariants (every leaf matched exactly
  once, scalars unpartitioned, specs valid for the mesh, aligned cuts
  tile at segment boundaries);
- HbmSlot mechanics (donation really consumes the old buffers, the
  per-version snapshot/pull caches really cache, pulls survive a later
  donated apply);
- **bitwise parity**: for msgd / DOWNPOUR / EAMSGD, a device-exchange
  run ends with exactly the bytes of the host-path run under a fixed
  reduction order — including a mixed gang where the wire-fallback
  server runs under a drop/dup FaultPlan (retry/dedup intact beside
  the device path).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.dplane import (
    ExchangeClient,
    ExchangeError,
    HbmSlot,
    PlaneConfig,
    aligned_cut,
    dedupe_state,
    flat_segments,
    match_partition_rules,
    match_report,
    plan_shard_map,
    tree_shardings,
)
from mpit_tpu.dplane.exchange import DevicePlane, DeviceTicket
from mpit_tpu.dplane.partition import Segment, shard_tree, validate_spec
from mpit_tpu.ft import FaultPlan, FaultyTransport, FTConfig
from mpit_tpu.optim.downpour import Downpour
from mpit_tpu.optim.easgd import EAMSGD
from mpit_tpu.optim.rules import make as make_rule
from mpit_tpu.optim.shells import SingleWorker
from mpit_tpu.parallel.mesh import make_mesh
from mpit_tpu.ps import ParamClient, ParamServer, tags
from mpit_tpu.utils.platform import default_devices

DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})
FAST_FT = FTConfig(op_deadline_s=0.25, max_retries=8,
                   backoff_base_s=0.005, backoff_cap_s=0.02)


def mesh8():
    return make_mesh(default_devices(), dp=1)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


def _tree(seed: int):
    """A transformer-shaped random pytree (nested dicts, mixed ranks,
    a couple of scalars)."""
    rng = np.random.default_rng(seed)
    return {
        "embed": {"table": rng.normal(size=(16, 8)).astype(np.float32)},
        "layer_0": {
            "attn": {"q": rng.normal(size=(8, 8)).astype(np.float32),
                     "bias": rng.normal(size=8).astype(np.float32)},
            "mlp": {"w1": rng.normal(size=(8, 16)).astype(np.float32),
                    "w2": rng.normal(size=(16, 8)).astype(np.float32)},
        },
        "norm": {"scale": np.float32(rng.normal())},
        "step": np.zeros((), np.int32),
    }


RULES = [
    (r"embed/table", P("shard", None)),
    (r"attn/.*bias", P(None)),
    (r"attn", P(None, "shard")),
    (r"mlp/w1", P(None, "shard")),
    (r"mlp/w2", P("shard", None)),
    (r".*", P()),
]


class TestPartitionRules:
    def test_first_match_wins_and_scalars_unpartitioned(self):
        specs = match_partition_rules(RULES, _tree(0))
        assert specs["embed"]["table"] == P("shard", None)
        # attn/bias hits the bias rule before the broader attn rule
        assert specs["layer_0"]["attn"]["bias"] == P(None)
        assert specs["layer_0"]["attn"]["q"] == P(None, "shard")
        # scalars resolve to P() without consuming a rule
        assert specs["norm"]["scale"] == P()
        assert specs["step"] == P()

    def test_unmatched_leaf_raises_or_replicates(self):
        rules = [(r"embed", P("shard", None))]
        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules(rules, _tree(0))
        specs = match_partition_rules(rules, _tree(0),
                                      on_unmatched="replicate")
        assert specs["layer_0"]["mlp"]["w1"] == P()

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_every_leaf_matched_exactly_once(self, seed):
        tree = _tree(seed)
        leaves = jax.tree_util.tree_leaves(tree)
        report = match_report(RULES, tree)
        # unique path per leaf => exactly one verdict per leaf
        assert len(report) == len(leaves)
        for name, idx in report.items():
            if name in ("norm/scale", "step"):
                assert idx == -1, name  # scalar: never partitioned
            else:
                assert 0 <= idx < len(RULES), name

    def test_specs_valid_for_mesh(self):
        mesh = mesh8()
        tree = _tree(0)
        specs = match_partition_rules(RULES, tree)
        shardings = tree_shardings(mesh, specs, tree)
        flat = jax.tree_util.tree_leaves(shardings)
        assert all(s.mesh.shape == mesh.shape for s in flat)
        # placement roundtrip preserves every byte
        placed = shard_tree(tree, shardings)
        for a, b in zip(jax.tree_util.tree_leaves(placed),
                        jax.tree_util.tree_leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_invalid_axis_and_indivisible_dims_fail_loudly(self):
        mesh = mesh8()
        with pytest.raises(ValueError, match="not in mesh axes"):
            validate_spec(mesh, P("bogus"), (8,), "x")
        with pytest.raises(ValueError, match="not divisible"):
            validate_spec(mesh, P("shard"), (9,), "x")
        with pytest.raises(ValueError, match="names 2 dims"):
            validate_spec(mesh, P("shard", None), (8,), "x")

    def test_naive_fallback_degrades_indivisible_dims(self):
        mesh = mesh8()
        tree = {"w": np.zeros((9, 8), np.float32)}
        specs = {"w": P("shard", None)}
        shardings = tree_shardings(mesh, specs, tree, naive_fallback=True)
        assert shardings["w"].spec == P(None, None)
        with pytest.raises(ValueError, match="not divisible"):
            tree_shardings(mesh, specs, tree)


class TestAlignedCut:
    @pytest.mark.parametrize("seed", [3, 4, 5])
    def test_cut_properties(self, seed):
        rng = np.random.default_rng(seed)
        sizes = rng.integers(1, 50, size=12)
        segments, off = [], 0
        for i, s in enumerate(sizes):
            segments.append(Segment(f"leaf{i}", off, int(s)))
            off += int(s)
        n = int(rng.integers(2, 6))
        shards = aligned_cut(off, segments, n)
        # tile [0, plong), nonempty, interior cuts on segment boundaries
        assert shards[0].offset == 0 and shards[-1].end == off
        boundaries = {s.offset for s in segments}
        pos = 0
        for sh in shards:
            assert sh.offset == pos and sh.size > 0
            assert sh.offset in boundaries or sh.offset == 0
            pos = sh.end
        # deterministic
        assert aligned_cut(off, segments, n) == shards

    def test_fewer_segments_than_shards_raises(self):
        segments = [Segment("a", 0, 10), Segment("b", 10, 10)]
        with pytest.raises(ValueError, match="never splits a parameter"):
            aligned_cut(20, segments, 3)

    def test_plan_shard_map_is_a_valid_layout_source(self):
        tree = _tree(1)
        smap = plan_shard_map(tree, [0, 1], shards_per_server=2)
        segments = flat_segments(tree)
        assert smap.plong == segments[-1].end
        assert smap.version == 0 and len(smap.entries) == 4
        assert [e.owner for e in smap.entries] == [0, 0, 1, 1]
        boundaries = {s.offset for s in segments}
        for e in smap.entries[1:]:
            assert e.shard.offset in boundaries


class TestHbmSlot:
    def test_donated_apply_consumes_old_buffers_bitwise(self):
        cfg = PlaneConfig(mesh=mesh8())
        slot = HbmSlot(16, make_rule("adam"), config=cfg)
        rng = np.random.default_rng(7)
        g = rng.normal(size=16).astype(np.float32)
        # reference: the same rule math, un-donated, on host arrays
        ref_rule = make_rule("adam")
        ref_p = jnp.zeros(16, jnp.float32)
        ref_s = ref_rule.init(ref_p)
        ref_p, ref_s = jax.jit(ref_rule.apply)(ref_p, jnp.asarray(g), ref_s)
        p0, m0 = slot.param, slot.rule_state["m"]
        slot.apply_grad(g)
        assert p0.is_deleted() and m0.is_deleted(), \
            "donation did not consume the old buffers"
        np.testing.assert_array_equal(slot.snapshot_host(),
                                      np.asarray(ref_p))
        assert slot.version == 1

    def test_snapshot_and_pull_caches_are_per_version(self):
        slot = HbmSlot(16, make_rule("add"), config=PlaneConfig(mesh=mesh8()))
        a, b = slot.snapshot_host(), slot.snapshot_host()
        assert a is b and int(slot._m_copies.value) == 1
        p1, p2 = slot.pull_device(), slot.pull_device()
        assert p1 is p2 and int(slot._m_gathers.value) == 1
        slot.apply_grad(np.ones(16, np.float32))
        assert slot.snapshot_host() is not a
        assert int(slot._m_copies.value) == 2

    def test_pull_survives_a_later_donated_apply(self):
        slot = HbmSlot(16, make_rule("add"), config=PlaneConfig(mesh=mesh8()))
        pulled = slot.pull_device()
        slot.apply_grad(np.ones(16, np.float32))
        # the old param buffer was donated away; the pull must not be it
        np.testing.assert_array_equal(np.asarray(pulled),
                                      np.zeros(16, np.float32))

    def test_dedupe_state_breaks_rule_init_aliasing(self):
        p = jnp.zeros(8, jnp.float32)
        state = make_rule("adam").init(p)
        assert state["m"] is state["v"], "fixture assumption: init aliases"
        fresh = dedupe_state(state)
        assert fresh["m"] is not fresh["v"]
        np.testing.assert_array_equal(np.asarray(fresh["m"]),
                                      np.asarray(fresh["v"]))


# ---------------------------------------------------------------------------
# the partition engine over a REAL TrainState (params + optimizer slots)


def _lm_train_state(rule="adam"):
    from mpit_tpu.lm import build, train_state_tree

    model = build(d_model=16, n_heads=2, n_layers=1, seq_len=16,
                  use_flash=False)
    params = model.flat.unravel(model.flat.w0)
    return params, train_state_tree(params, rule)


class TestTrainStatePartition:
    """The rule table must cover params AND the mirrored optimizer
    slots — the tree the LM shard plan is actually computed over."""

    @pytest.mark.parametrize("rule", ["adam", "rmsprop", "adagrad"])
    def test_every_trainstate_leaf_matched_exactly_once(self, rule):
        from mpit_tpu.lm import PARTITION_RULES, audit_rules

        params, ts = _lm_train_state(rule)
        leaves = jax.tree_util.tree_leaves(ts)
        report = audit_rules(ts)  # raises on any -2 (unmatched)
        assert len(report) == len(leaves)
        for name, idx in report.items():
            assert idx == -1 or 0 <= idx < len(PARTITION_RULES), name
        # optimizer slots mirror the param paths, so both halves of the
        # TrainState resolve through ONE table
        assert any(n.startswith("params/") and report[n] >= 0
                   for n in report)
        assert any(n.startswith("opt_state/") and report[n] >= 0
                   for n in report)
        # per-leaf step counters are scalars: unpartitioned, not errors
        assert all(report[n] == -1 for n in report if n.endswith("/t"))

    def test_unmatched_opt_leaf_is_loud(self):
        from mpit_tpu.lm import audit_rules

        _, ts = _lm_train_state("adam")
        # drop the kernel rule: every Dense kernel (params AND its m/v
        # slots) must be reported, not silently replicated
        rules = [(r"Embed_\d+/embedding", P("mdl", None)),
                 (r"Dense_\d+/bias", P()),
                 (r"LayerNorm_\d+/(scale|bias)", P())]
        with pytest.raises(ValueError, match="match no partition rule"):
            audit_rules(ts, rules)

    def test_optax_style_nested_opt_state(self):
        optax = pytest.importorskip("optax")
        from mpit_tpu.lm import PARTITION_RULES

        params, _ = _lm_train_state()
        state = optax.adam(1e-3).init(params)
        tree = {"params": params, "opt_state": state}
        report = match_report(PARTITION_RULES, tree)
        leaves = jax.tree_util.tree_leaves(tree)
        assert len(report) == len(leaves)
        assert not any(idx == -2 for idx in report.values()), \
            sorted(n for n, i in report.items() if i == -2)
        # optax nests the param tree under namedtuple fields (mu/nu);
        # the component-name rules still land because match is a search
        mu = [n for n in report if "/mu/" in n]
        assert mu and all(report[n] >= 0 for n in mu)
        assert report["opt_state/0/count"] == -1  # scalar step counter

    def test_shared_zero_slots_compose_with_dedupe_state(self):
        # train_state_tree keeps rule-init aliasing (m is v is one
        # zeros_like); dedupe_state must break it leaf-by-leaf without
        # changing bytes — the seam a donated apply depends on.
        _, ts = _lm_train_state("adam")
        aliased = 0
        for _path, sub in jax.tree_util.tree_leaves_with_path(
                ts["opt_state"],
                is_leaf=lambda x: isinstance(x, dict) and "m" in x):
            if not isinstance(sub, dict):
                continue
            if sub["m"] is sub["v"]:
                aliased += 1
                fresh = dedupe_state(sub)
                assert fresh["m"] is not fresh["v"]
                np.testing.assert_array_equal(np.asarray(fresh["m"]),
                                              np.asarray(sub["m"]))
        assert aliased > 0, "fixture assumption: adam init aliases m/v"


# ---------------------------------------------------------------------------
# optimizer parity: device exchange vs host path, bitwise


def _quadratic(target):
    def vgf(w):
        delta = w - target
        return 0.5 * jnp.sum(delta * delta), delta

    return vgf


def _single_client_gang(dplane, *, rule="add", single_mode=False,
                        seed_servers=True):
    router = LocalRouter(3)
    sranks, crank = [0, 1], 2
    cfg = PlaneConfig.auto() if dplane else None
    servers = [ParamServer(r, [crank], router.endpoint(r), rule=rule,
                           single_mode=single_mode, dplane=cfg)
               for r in sranks]
    threads = [threading.Thread(target=s.start, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    pc = ParamClient(crank, sranks, router.endpoint(crank),
                     seed_servers=seed_servers)
    client = ExchangeClient(pc) if dplane else pc
    return servers, client, threads


def _run_optimizer(make_opt, dplane, steps=6, size=32):
    servers, client, threads = (
        _single_client_gang(dplane, rule="add"))
    rng = np.random.default_rng(21)
    w = jnp.asarray(rng.normal(size=size).astype(np.float32))
    target = jnp.asarray(rng.normal(size=size).astype(np.float32))
    opt = make_opt(_quadratic(target), client)
    w = opt.start(w)
    for _ in range(steps):
        w, _loss = opt.step(w)
    opt.stop()
    join_all(threads)
    if dplane:
        assert client.device_ranks == [0, 1]
    finals = [np.asarray(s.param) for s in servers]
    return np.asarray(w), np.concatenate(finals)


@pytest.mark.parametrize("name,make_opt", [
    ("downpour", lambda vgf, pc: Downpour(vgf, pc, lr=0.05, su=2)),
    ("eamsgd", lambda vgf, pc: EAMSGD(vgf, pc, lr=0.05, mom=0.5,
                                      mva=0.3, su=2)),
])
def test_optimizer_parity_device_vs_host(name, make_opt):
    """DOWNPOUR / EAMSGD: the device-exchange run must end bitwise
    equal to the host-path run — local params AND the servers' center."""
    w_host, center_host = _run_optimizer(make_opt, dplane=False)
    w_dev, center_dev = _run_optimizer(make_opt, dplane=True)
    np.testing.assert_array_equal(w_host, w_dev)
    np.testing.assert_array_equal(center_host, center_dev)


def _run_msgd(dplane, steps=5, size=32):
    servers, client, threads = _single_client_gang(
        dplane, single_mode=True, seed_servers=True)
    rng = np.random.default_rng(33)
    w = jnp.asarray(rng.normal(size=size).astype(np.float32))
    target = jnp.asarray(rng.normal(size=size).astype(np.float32))
    opt = SingleWorker(_quadratic(target), client, rule="msgd",
                       lr=0.05, mom=0.9)
    w = opt.start(w)
    for _ in range(steps):
        w, _loss = opt.step(w)
    opt.stop()
    join_all(threads)
    return np.asarray(w), np.concatenate(
        [np.asarray(s.param) for s in servers])


def test_msgd_parity_device_vs_host():
    """msgd (SingleWorker): whole-param pushes ride the device 'push'
    op; the mirrored server state must match the host run bitwise."""
    w_host, mirror_host = _run_msgd(dplane=False)
    w_dev, mirror_dev = _run_msgd(dplane=True)
    np.testing.assert_array_equal(w_host, w_dev)
    np.testing.assert_array_equal(mirror_host, mirror_dev)
    np.testing.assert_array_equal(w_dev, mirror_dev)


# ---------------------------------------------------------------------------
# mixed gang: device path beside the faulty wire fallback


def _mixed_gang_final(device_ranks, client_plans, rounds=4, size=64):
    """2 servers / 2 clients lockstep; server ranks in ``device_ranks``
    serve over the device path, the rest over the (possibly faulty)
    framed wire."""
    router = LocalRouter(4)
    sranks, cranks = [0, 1], [2, 3]
    cfg = PlaneConfig.auto() if device_ranks else None
    servers = [ParamServer(r, cranks, router.endpoint(r), rule="add",
                           ft=FAST_FT, dplane=cfg) for r in sranks]
    threads = [threading.Thread(target=s.start, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    rng = np.random.default_rng(42)
    w0 = rng.normal(size=size).astype(np.float32)
    gtab = rng.normal(size=(2, rounds, size)).astype(np.float32)
    clients = []
    for r in cranks:
        ep = router.endpoint(r)
        if client_plans and r - 2 in client_plans:
            ep = FaultyTransport(ep, client_plans[r - 2])
        pc = ParamClient(r, sranks, ep, seed_servers=(r == cranks[0]),
                         ft=FAST_FT)
        clients.append(ExchangeClient(pc, device_ranks=device_ranks)
                       if device_ranks else pc)
    params = [w0.copy(), np.zeros(size, np.float32)]
    starters = [threading.Thread(target=c.start,
                                 args=(p, np.zeros(size, np.float32)),
                                 daemon=True)
                for c, p in zip(clients, params)]
    for t in starters:
        t.start()
    join_all(starters)
    for r in range(rounds):
        for i, c in enumerate(clients):
            c.grad[:] = gtab[i, r]
            c.async_send_grad()
            c.wait()
    clients[0].async_recv_param()
    clients[0].wait()
    final = clients[0].param.copy()
    retries = sum(c.retries for c in clients)
    for c in clients:
        c.stop()
    join_all(threads)
    return final, retries, servers


def test_faultplan_leg_mixed_device_and_faulty_wire_bitwise():
    """The ISSUE's drop/dup leg: server 0 serves on the device path,
    server 1 on the wire under a drop/dup FaultPlan.  Final params must
    equal the fault-free all-wire run bitwise — retry/dedup cover the
    wire half while the device half bypasses it entirely."""
    clean, _, _ = _mixed_gang_final(None, None)
    plans = {i: FaultPlan(seed=i, drop_every=3, dup_every=4,
                          tags=DATA_TAGS) for i in range(2)}
    faulty, retries, servers = _mixed_gang_final([0], plans)
    np.testing.assert_array_equal(clean, faulty)
    assert retries > 0, "the plan never actually bit"
    dev_ops = sum(int(c.value) for c in servers[0]._m_dp_ops.values())
    assert dev_ops > 0, "the device path was never exercised"
    assert servers[1]._hbm is None or not servers[1]._m_dp_ops, \
        "the faulty server must have served over the wire"


# ---------------------------------------------------------------------------
# exchange lifecycle: loud failures, honest fallbacks


class TestExchangeLifecycle:
    def test_closed_plane_fails_tickets_loudly(self):
        plane = DevicePlane(0, (0, "cpu"))
        ticket = plane.submit(DeviceTicket("grad", 1, 0, None))
        plane.close("test teardown")
        assert ticket.event.is_set()
        assert isinstance(ticket.error, ExchangeError)
        with pytest.raises(ExchangeError, match="closed"):
            plane.submit(DeviceTicket("grad", 1, 0, None))

    def test_non_identity_codec_falls_back_to_wire(self):
        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0), rule="add",
                             codec=None, dplane=PlaneConfig.auto())
        t = threading.Thread(target=server.start, daemon=True)
        t.start()
        pc = ParamClient(1, [0], router.endpoint(1), seed_servers=True,
                         codec="int8")
        client = ExchangeClient(pc)
        w = np.zeros(2048, np.float32)
        client.start(w, np.zeros_like(w))
        assert client.device_ranks == []  # quantized exchange: wire only
        client.grad[:] = 1.0
        client.async_send_grad()
        client.wait()
        client.stop()
        join_all([t])

    def test_require_device_raises_without_a_plane(self):
        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0), rule="add")
        t = threading.Thread(target=server.start, daemon=True)
        t.start()
        pc = ParamClient(1, [0], router.endpoint(1), seed_servers=True)
        client = ExchangeClient(pc, require_device=True)
        w = np.zeros(16, np.float32)
        with pytest.raises(ExchangeError, match="fell back to the wire"):
            client.start(w, np.zeros_like(w))
        client.stop()
        join_all([t])

    def test_sync_device_round_stays_on_device(self):
        servers, client, threads = _single_client_gang(True)
        w0 = np.ones(32, np.float32)
        client.start(w0.copy(), np.zeros(32, np.float32))
        update = jnp.full(32, 0.5, jnp.float32)
        out = client.sync_device(update)
        assert isinstance(out, jax.Array)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.full(32, 1.5, np.float32))
        client.stop()
        join_all(threads)
