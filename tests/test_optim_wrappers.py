"""Tests for the comm-aware optimizers against an in-process fake client.

The fake implements the ParamClientAPI protocol backed by a single "server"
center vector with plain-add semantics and deferred (queued) transfer
execution — enough to verify the wrappers' *algebra* against sequential
simulators, independent of the real transport (which gets its own tests).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.optim.client_api import ParamClientAPI
from mpit_tpu.optim.downpour import Downpour
from mpit_tpu.optim.easgd import EAMSGD
from mpit_tpu.optim.shells import RuleShell, SingleWorker


class FakeClient:
    """Single-shard plain-add server with queued async ops."""

    def __init__(self):
        self.center = None
        self.ops = []
        self.stopped = False

    def start(self, param, grad):
        self.param_buf = param
        self.grad_buf = grad
        self.center = param.copy()  # first client seeds the server

    def reset(self, param, grad):
        self.param_buf = param
        self.grad_buf = grad

    def async_send_grad(self):
        self.ops.append("send_grad")

    def async_recv_param(self):
        self.ops.append("recv_param")

    def async_send_param(self):
        self.ops.append("send_param")

    def _run(self, op):
        if op == "send_grad":
            self.center += self.grad_buf
        elif op == "recv_param":
            np.copyto(self.param_buf, self.center)
        elif op == "send_param":
            np.copyto(self.center, self.param_buf)

    def ping(self):
        if self.ops:
            self._run(self.ops.pop(0))

    def wait(self):
        while self.ops:
            self._run(self.ops.pop(0))

    def stop(self):
        self.stopped = True


def quadratic_vgf(w, target):
    loss = 0.5 * jnp.sum((w - target) ** 2)
    return loss, w - target


@pytest.fixture
def w0(rng):
    return rng.normal(size=6).astype(np.float32)


@pytest.fixture
def target():
    return jnp.zeros(6, jnp.float32)


class TestDownpour:
    def test_su1_matches_serial_sgd(self, w0, target):
        """One worker, su=1: center and worker follow plain SGD exactly."""
        lr = 0.1
        pc = FakeClient()
        opt = Downpour(quadratic_vgf, pc, lr=lr, su=1)
        w = opt.start(jnp.asarray(w0))
        for _ in range(4):
            w, _ = opt.step(w, target)
        ref = w0.astype(np.float64)
        for _ in range(4):
            ref = ref - lr * ref  # grad of quadratic at target 0 is w
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)
        np.testing.assert_allclose(pc.center, ref, rtol=1e-4)

    def test_su3_accumulates_and_moves_locally(self, w0, target):
        lr, su, steps = 0.05, 3, 7
        pc = FakeClient()
        opt = Downpour(quadratic_vgf, pc, lr=lr, su=su)
        w = opt.start(jnp.asarray(w0))
        for _ in range(steps):
            w, _ = opt.step(w, target)

        # Sequential simulator of reference optim-downpour.lua:26-45.
        center = w0.astype(np.float64).copy()
        ref = w0.astype(np.float64).copy()
        accum = np.zeros(6)
        for k in range(steps):
            dfdx = -lr * ref
            accum = accum + dfdx
            if k % su == 0:
                center = center + accum
                ref = center.copy()
                accum[:] = 0
            else:
                ref = ref + dfdx
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)
        np.testing.assert_allclose(pc.center, center, rtol=1e-4)

    def test_lr_decay(self, w0, target):
        lr, lrd = 0.1, 0.5
        pc = FakeClient()
        opt = Downpour(quadratic_vgf, pc, lr=lr, lrd=lrd, su=1)
        w = opt.start(jnp.asarray(w0))
        for _ in range(3):
            w, _ = opt.step(w, target)
        ref = w0.astype(np.float64)
        for k in range(3):
            ref = ref - lr / (1 + k * lrd) * ref
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)

    def test_su_validation(self):
        with pytest.raises(ValueError):
            Downpour(quadratic_vgf, FakeClient(), lr=0.1, su=0)


class TestEAMSGD:
    def test_elastic_algebra_one_round(self, w0, target):
        """One sync round: sug = mva*(w - w*); center += sug; w_local
        updated by Nesterov-less SGD then retracted by sug."""
        lr, mva = 0.1, 0.25
        pc = FakeClient()
        opt = EAMSGD(quadratic_vgf, pc, lr=lr, mva=mva, su=1)
        w = opt.start(jnp.asarray(w0))
        center0 = pc.center.copy()  # == w0
        w, _ = opt.step(w, target)

        sug = mva * (w0 - center0)  # zero on the very first round
        expected_center = center0 + sug
        expected_w = (w0 - lr * w0) - sug
        np.testing.assert_allclose(np.asarray(w), expected_w, rtol=1e-4)
        opt.pc.wait()
        np.testing.assert_allclose(pc.center, expected_center, rtol=1e-4)

    def test_su2_matches_simulator(self, w0, target):
        lr, mva, mom, su, steps = 0.05, 0.2, 0.9, 2, 6
        pc = FakeClient()
        opt = EAMSGD(quadratic_vgf, pc, lr=lr, mva=mva, mom=mom, su=su)
        w = opt.start(jnp.asarray(w0))
        for _ in range(steps):
            w, _ = opt.step(w, target)
        opt.pc.wait()

        # Sequential simulator of reference optim-eamsgd.lua:47-69.
        center = w0.astype(np.float64).copy()
        ref = w0.astype(np.float64).copy()
        vt = np.zeros(6)
        k = 0
        for _ in range(steps):
            sync = k % su == 0
            if sync:
                sug = mva * (ref - center)
                center = center + sug
            # localupdate (Nesterov, no ramp)
            vt = mom * vt
            ref = ref + vt
            g = ref  # quadratic grad at lookahead
            ref = ref - lr * g
            vt = vt - lr * g
            k += 1
            if sync:
                ref = ref - sug
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)
        np.testing.assert_allclose(pc.center, center, rtol=1e-4)

    def test_requires_mva_and_su(self):
        with pytest.raises(ValueError):
            EAMSGD(quadratic_vgf, FakeClient(), lr=0.1, mva=0.0, su=1)

    def test_comm_only_fused_elastic_matches(self, w0, target, monkeypatch):
        """lr=0 (comm-only, reference :25): the fused one-sweep
        force+retract matches the two-op path."""
        finals = {}
        for env in ("0", "1"):
            monkeypatch.setenv("MPIT_FUSED", env)
            pc = FakeClient()
            opt = EAMSGD(quadratic_vgf, pc, lr=0.0, mva=0.3, su=1)
            assert opt._use_fused_elastic is (env == "1")
            w = opt.start(jnp.asarray(w0))
            for _ in range(3):
                w, _ = opt.step(w, target)
            opt.pc.wait()
            finals[env] = (np.asarray(w), pc.center.copy())
        np.testing.assert_allclose(finals["1"][0], finals["0"][0], atol=1e-6)
        np.testing.assert_allclose(finals["1"][1], finals["0"][1], atol=1e-6)


class TestRuleShell:
    def test_global_su1_ships_raw_grads(self, w0, target):
        pc = FakeClient()
        shell = RuleShell(quadratic_vgf, pc, su=1, mode="global")
        w = shell.start(jnp.asarray(w0))
        w, _ = shell.step(w, target)
        # Plain-add fake server: center += raw grad (= w0 here).
        np.testing.assert_allclose(pc.center, w0 + w0, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(w), pc.center, rtol=1e-4)

    def test_global_su3_accumulates(self, w0, target):
        su, steps = 3, 5
        pc = FakeClient()
        shell = RuleShell(quadratic_vgf, pc, su=su, mode="global")
        w = shell.start(jnp.asarray(w0))
        for _ in range(steps):
            w, _ = shell.step(w, target)

        center = w0.astype(np.float64).copy()
        ref = w0.astype(np.float64).copy()
        accum = np.zeros(6)
        for k in range(steps):
            g = ref
            accum = accum + g
            if k % su == 0:
                center = center + accum
                ref = center.copy()
                accum[:] = 0
            # else params do not move
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)

    def test_local_rmsprop_su1(self, w0, target):
        lr, decay, momentum, eps = 0.01, 0.9, 0.5, 1e-4
        pc = FakeClient()
        shell = RuleShell(
            quadratic_vgf, pc, su=1, mode="local",
            lr=lr, decay=decay, momentum=momentum, epsilon=eps,
        )
        w = shell.start(jnp.asarray(w0))
        w, _ = shell.step(w, target)
        # update = centered-rmsprop step on g=w0; center += update; w = center.
        g = w0.astype(np.float64)
        ga = (1 - decay) * g
        gsa = (1 - decay) * g * g
        rms = np.sqrt(gsa - ga * ga + eps)
        update = -lr * g / rms
        np.testing.assert_allclose(pc.center, w0 + update, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(w), pc.center, rtol=1e-4)

    def test_mode_validation(self):
        with pytest.raises(ValueError):
            RuleShell(quadratic_vgf, FakeClient(), mode="bogus")


class TestSingleWorker:
    def test_adam_pushes_params_to_mirror(self, w0, target):
        pc = FakeClient()
        opt = SingleWorker(
            quadratic_vgf, pc, rule="adam", lr=1e-2, beta1=0.9, beta2=0.999,
            epsilon=1e-8,
        )
        w = opt.start(jnp.asarray(w0))
        for _ in range(3):
            w, _ = opt.step(w, target)
        # Server mirror tracks local params exactly.
        np.testing.assert_allclose(pc.center, np.asarray(w), rtol=1e-5)

    def test_msgd_single(self, w0, target):
        pc = FakeClient()
        opt = SingleWorker(quadratic_vgf, pc, rule="msgd", lr=0.1, mom=0.9)
        w = opt.start(jnp.asarray(w0))
        w, _ = opt.step(w, target)
        np.testing.assert_allclose(pc.center, np.asarray(w), rtol=1e-5)
