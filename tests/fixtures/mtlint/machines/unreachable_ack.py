"""Seeded MT-M702: the client declares a recv for W_ACK, but the server
table never sends it — the ack transition is dead protocol surface.  A
tau escape keeps the machine deadlock-free so the unreachable-ack
detector is what fires (mtlint fixture — plain machine data)."""

MACHINES = [
    {
        "name": "seeded-unreachable-ack",
        "doc": "declared ack recv that no execution can reach",
        "channel_cap": 2,
        "roles": {
            "client": {
                "start": "running",
                "terminal": ["done"],
                "transitions": [
                    ("running", "send", "W", "server", "sent", {}),
                    ("sent", "recv", "W_ACK", "server", "done", {}),
                    ("sent", "tau", "give_up", "", "done", {}),
                ],
            },
            "server": {
                "start": "serving",
                "terminal": ["done"],
                "transitions": [
                    ("serving", "recv", "W", "client", "done", {}),
                ],
            },
        },
    },
]
