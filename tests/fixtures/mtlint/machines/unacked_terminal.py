"""Seeded MT-M703: the client's write declares an expected ack
(``expects: W_ACK``) but the table lets both roles reach terminal rest
without the ack ever being sent or received — the write completion is
unobservable at quiescence (mtlint fixture — plain machine data)."""

MACHINES = [
    {
        "name": "seeded-unacked-terminal",
        "doc": "terminal rest with a declared ack still outstanding",
        "channel_cap": 2,
        "roles": {
            "client": {
                "start": "running",
                "terminal": ["done"],
                "transitions": [
                    ("running", "send", "W", "server", "done",
                     {"expects": "W_ACK"}),
                ],
            },
            "server": {
                "start": "serving",
                "terminal": ["done"],
                "transitions": [
                    ("serving", "recv", "W", "client", "done", {}),
                ],
            },
        },
    },
]
