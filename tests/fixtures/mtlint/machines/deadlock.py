"""Seeded MT-M701: the classic recv-recv wait cycle — the client blocks
on the reply before sending the request, while the server sends the
reply only after receiving the request.  Neither side can move from the
initial state (mtlint fixture — plain machine data, never imported by
the tree)."""

MACHINES = [
    {
        "name": "seeded-recv-recv-deadlock",
        "doc": "both roles wait on the other's send",
        "channel_cap": 2,
        "roles": {
            "client": {
                "start": "want",
                "terminal": ["done"],
                "transitions": [
                    ("want", "recv", "REPLY", "server", "got", {}),
                    ("got", "send", "REQ", "server", "done", {}),
                ],
            },
            "server": {
                "start": "serving",
                "terminal": ["done"],
                "transitions": [
                    ("serving", "recv", "REQ", "client", "replying", {}),
                    ("replying", "send", "REPLY", "client", "done", {}),
                ],
            },
        },
    },
]
