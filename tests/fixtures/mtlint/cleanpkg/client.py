"""Clean client role (mtlint fixture — zero findings expected)."""

import tags
from aio import aio_recv, aio_send


def send_grad(transport, grad, live, deadline):
    yield from aio_send(transport, grad, 0, tags.GRAD, live=live,
                        deadline=deadline)
    yield from aio_recv(transport, 0, tags.GRAD_ACK, live=live,
                        deadline=deadline)


def recv_param(transport, out, live, deadline):
    yield from aio_send(transport, b"", 0, tags.PARAM_REQ, live=live,
                        deadline=deadline)
    yield from aio_recv(transport, 0, tags.PARAM, live=live, out=out,
                        deadline=deadline)


def _post_chunk(transport, frame, live, deadline):
    # Helper-split write (the §12 chunk-post shape): the naked GRAD send
    # is vouched for by stream_grads' ack drain one call level up.
    yield from aio_send(transport, frame, 0, tags.GRAD, live=live,
                        deadline=deadline)


def _drain_acks(transport, live, deadline):
    yield from aio_recv(transport, 0, tags.GRAD_ACK, live=live,
                        deadline=deadline)


def stream_grads(transport, frames, live, deadline):
    for frame in frames:
        yield from _post_chunk(transport, frame, live, deadline)
    yield from _drain_acks(transport, live, deadline)
