"""Clean client role (mtlint fixture — zero findings expected)."""

import tags
from aio import aio_recv, aio_send


def send_grad(transport, grad, live, deadline):
    yield from aio_send(transport, grad, 0, tags.GRAD, live=live,
                        deadline=deadline)
    yield from aio_recv(transport, 0, tags.GRAD_ACK, live=live,
                        deadline=deadline)


def recv_param(transport, out, live, deadline):
    yield from aio_send(transport, b"", 0, tags.PARAM_REQ, live=live,
                        deadline=deadline)
    yield from aio_recv(transport, 0, tags.PARAM, live=live, out=out,
                        deadline=deadline)
