"""Clean server role (mtlint fixture — zero findings expected)."""

import tags
from aio import aio_recv, aio_send


def serve_grad(transport, buf, live):
    got = yield from aio_recv(transport, 1, tags.GRAD, out=buf, live=live)
    yield from aio_send(transport, b"", 1, tags.GRAD_ACK, live=live)
    return got


def serve_param(transport, snapshot, live):
    yield from aio_recv(transport, 1, tags.PARAM_REQ, live=live)
    yield from aio_send(transport, snapshot, 1, tags.PARAM, live=live)
