"""Clean server role (mtlint fixture — zero findings expected)."""

import tags
from aio import aio_recv, aio_send


def serve_grad(transport, buf, live, gone):
    got = yield from aio_recv(transport, 1, tags.GRAD, out=buf, live=live,
                              abort=gone)
    yield from aio_send(transport, b"", 1, tags.GRAD_ACK, live=live,
                        abort=gone)
    return got


def serve_param(transport, snapshot, live, gone):
    yield from aio_recv(transport, 1, tags.PARAM_REQ, live=live, abort=gone)
    yield from aio_send(transport, snapshot, 1, tags.PARAM, live=live,
                        abort=gone)
