"""Clean server role (mtlint fixture — zero findings expected)."""

import tags
from aio import aio_recv, aio_send


def serve_grad(transport, buf, live, gone):
    got = yield from aio_recv(transport, 1, tags.GRAD, out=buf, live=live,
                              abort=gone)
    yield from aio_send(transport, b"", 1, tags.GRAD_ACK, live=live,
                        abort=gone)
    return got


def serve_param(transport, snapshot, live, gone):
    yield from aio_recv(transport, 1, tags.PARAM_REQ, live=live, abort=gone)
    yield from aio_send(transport, snapshot, 1, tags.PARAM, live=live,
                        abort=gone)


def _send_ack_tail(transport, peer, tag, live, gone):
    # Tag travels as a parameter (the _send_chunk_ack shape): resolved
    # at the call site by the interprocedural scan.
    yield from aio_send(transport, b"", peer, tag, live=live, abort=gone)


def serve_grad_chunks(transport, buf, live, gone):
    got = yield from aio_recv(transport, 1, tags.GRAD, out=buf, live=live,
                              abort=gone)
    yield from _send_ack_tail(transport, 1, tags.GRAD_ACK, live, gone)
    return got
