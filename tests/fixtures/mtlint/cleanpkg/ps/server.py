"""Clean yield-atomicity + ownership twins (mtlint fixture — zero
findings).  Same declared-discipline surface as badpkg/ps/server.py:
the read-gate window stays yield-free (``sched.spawn`` of a generator
is NOT a yield — spawn primes only the new task), the plane pop stays
inside the single-writer closure even one helper down, and every buffer
crossing the donation seam is provably owned."""

import numpy as np

EXEC = "EXEC"


class PS:
    def _read_gate(self):
        if self.lag > self.bound:
            return None
        return self.version

    def _serve_ok_header(self, version):
        return (version, len(self._wire))

    def _snapshot_wire(self):
        return self._wire

    def _dispatch_read(self, req):
        gate = self._read_gate()
        header = self._serve_ok_header(gate)
        # spawn primes the NEW task one step; it does not yield this one.
        self.sched.spawn(
            self._serve_reply(req, header, self._snapshot_wire()))

    def _serve_reply(self, req, header, wire):
        yield EXEC
        req.reply(header, wire)

    def _reader_dispatcher(self):
        while self.live:
            req = yield EXEC
            self._dispatch_read(req)

    def _drain_once(self):
        ticket = self._plane.pop()
        if ticket is not None:
            self.execute(ticket)

    def _dplane_service(self):
        while self.live:
            yield EXEC
            self._drain_once()

    def _chunk_owned(self, view):
        return np.array(view)

    def _staged(self, blob):
        out = np.empty(len(blob) // 4, np.float32)
        self.codec.decode_into(blob, out)
        return out

    def good_apply(self, codec, view, lo):
        self._hbm.apply_wire_chunk(codec, self._chunk_owned(view), lo)

    def staged_apply(self, codec, blob, lo):
        self._hbm.apply_wire_chunk(codec, self._staged(blob), lo)

    def _recv_param_chunked(self, codec, asm, lo, hi, blob):
        # The owning snapshot exists only as the pool submit argument —
        # the declared pool-server-scatter-owned shape.
        self.pool.submit_scatter(
            codec, asm, self.size, lo, hi, np.array(blob))
