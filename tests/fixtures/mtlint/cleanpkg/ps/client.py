"""Clean pooled-decode twin (mtlint fixture — zero findings): the
owning snapshot of the rx frame is constructed exactly at the pool
submit boundary."""

import numpy as np


class Client:
    def _chunked_read(self, body, out, lo, hi):
        return self.pool.submit_decode(
            self.codec, np.array(body), out[lo:hi])
