"""Clean event-loop callbacks (mtlint fixture — zero findings): raw
socket calls live in guarded _nb_* helpers; _el_* callbacks only ever
dispatch through them."""


class CleanLoop:
    @staticmethod
    def _nb_recv_into(sock, view):
        try:
            return sock.recv_into(view)
        except BlockingIOError:
            return None

    @staticmethod
    def _nb_send(sock, bufs):
        try:
            return sock.sendmsg(bufs)
        except BlockingIOError:
            return None

    def _el_on_readable(self, conn):
        return self._nb_recv_into(conn.sock, conn.view)

    def _el_on_writable(self, conn):
        return self._nb_send(conn.sock, conn.bufs)

    def _drain_via(self, conn):
        # Two helper levels below the callback, still routed through the
        # guarded _nb_* seam: the interprocedural scan stays silent.
        return self._nb_recv_into(conn.sock, conn.view)

    def _el_on_timer(self, conn):
        return self._drain_via(conn)
