"""Clean dplane fixture: the hot paths stay in HBM; host transfers live
only in name-exempted snapshot/timing code (mtlint MT-J31x)."""

import jax
import jax.numpy as jnp
import numpy as np


def apply_update(param, grad, state):
    return param + jnp.asarray(grad), state


def pull_exchange(slot):
    return jax.jit(lambda p: p)(slot.param)


def snapshot_host(slot):
    return np.asarray(slot.param)


def bench_timed(x):
    x.block_until_ready()
    return x
