"""Clean donation-seam twins under dplane/ (mtlint fixture — zero
findings): host values are copied onto device before entering the
donated apply chain, and slot readers materialize or replicate before
the next apply donates the buffer."""

import numpy as np


class HbmSlot:
    def __init__(self, n, config):
        self.config = config
        self.version = 0
        self.param = device_copy(
            place_flat(np.zeros((n,), np.float32), config))

    def seed(self, value):
        self.param = device_copy(place_flat(value, self.config))

    def snapshot_host(self):
        self._snap = (self.version, np.asarray(self.param))
        return self._snap[1]

    def pull_device(self):
        return self._replicate(self.param)
