"""Clean XOR-kernel twin (mtlint fixture — zero findings): the kernel
writes into a fresh owned buffer (copy-on-write frames)."""

import numpy as np


def good_delta(pool, a, b):
    out = np.empty(len(a), np.uint8)
    pool.xor_sync(a, b, out)
    return out
