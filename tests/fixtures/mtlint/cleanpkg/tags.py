"""Clean tag table (mtlint fixture — every channel fully paired)."""

GRAD = 1
GRAD_ACK = 2
PARAM_REQ = 3
PARAM = 4

# Conformance pairing table (MT-P5xx): complete, so the clean fixture
# stays silent.
TAG_PAIRS = {
    "GRAD": ("client", "server"),
    "GRAD_ACK": ("server", "client"),
    "PARAM_REQ": ("client", "server"),
    "PARAM": ("server", "client"),
}
