"""Clean tag table (mtlint fixture — every channel fully paired)."""

GRAD = 1
GRAD_ACK = 2
PARAM_REQ = 3
PARAM = 4
