"""The compliant preemption-notice shape (MT-P204 must stay silent):
the SIGTERM handler only sets flags and pokes a pre-opened wake pipe;
timestamping and the checkpoint/drain work happen on the serving
thread's next poll."""

import os
import signal


class Notice:
    def __init__(self, wake_fd: int = -1):
        self.notified = False
        self._wake_fd = wake_fd

    def _on_sigterm(self, signum, frame):
        self.notified = True
        if self._wake_fd >= 0:
            os.write(self._wake_fd, b"\x01")


NOTICE = Notice()
signal.signal(signal.SIGTERM, NOTICE._on_sigterm)
