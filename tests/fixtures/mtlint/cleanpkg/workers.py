"""Clean concurrency + hot-path module (mtlint fixture — zero findings).

Locks nest in one consistent order, blocking work happens outside lock
regions, and the jitted update donates its buffers.
"""

import threading
import time

import jax


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self.jobs = []

    def push(self, job):
        with self._lock:
            with self._cv:  # always _lock -> _cv, never inverted
                self.jobs.append(job)
                self._cv.notify()

    def idle(self):
        time.sleep(0.01)  # blocking, but no lock held

    def slow_flush(self):
        time.sleep(0.01)  # blocking, but callers only reach it lock-free

    def flush_outside(self):
        with self._lock:
            self.jobs.clear()
        self.slow_flush()  # helper blocks, lock already released


def update(w, g):
    return w - 0.1 * g


apply_update = jax.jit(update, donate_argnums=(0,))


def report(registry):
    # Cataloged metric (docs/OBSERVABILITY.md names it): MT-O403 silent.
    registry.counter("mpit_clean_jobs_total").inc()


def trace_clean_phase(span):
    # Cataloged span phase (docs/OBSERVABILITY.md names it): MT-O404
    # stays silent.
    span.mark("clean_phase")
