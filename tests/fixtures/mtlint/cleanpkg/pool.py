"""Clean worker-pool wait twins (mtlint fixture — zero findings):
nonblocking polls are fine under a lock, blocking collection happens
lock-free, and close joins the workers outside the mutex (the shape
comm/pool.py's ``close()`` uses)."""

import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self.job = None
        self.pool = None

    def poll_under_lock(self):
        with self._lock:
            return self.job.done()  # nonblocking probe — fine under a lock

    def collect(self):
        self.job.result()  # blocking wait with no lock held

    def close(self):
        with self._lock:
            pool, self.pool = self.pool, None
        if pool is not None:
            self.native.mt_pool_close(pool)  # join outside the mutex
