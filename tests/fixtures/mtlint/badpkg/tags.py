"""Seeded-violation tag table (mtlint fixture — never imported)."""

PING = 1  # seeded MT-P102: client sends, server never receives
GRAD = 2
GRAD_ACK = 3
REQ = 4
REPLY = 5
ORPHAN = 6  # seeded MT-P101: defined, never used by any role
