"""Seeded-violation tag table (mtlint fixture — never imported)."""

PING = 1  # seeded MT-P102: client sends, server never receives
GRAD = 2
GRAD_ACK = 3
REQ = 4
REPLY = 5
ORPHAN = 6  # seeded MT-P101: defined, never used by any role
ROGUE = 7  # seeded MT-P501/MT-P502: used by both roles, registered nowhere
PARAM_PUSH = 8
PARAM_PUSH_ACK = 9

# Conformance pairing table (MT-P5xx): ROGUE is deliberately absent.
TAG_PAIRS = {
    "PING": ("client", "server"),
    "GRAD": ("client", "server"),
    "GRAD_ACK": ("server", "client"),
    "REQ": ("client", "server"),
    "REPLY": ("server", "client"),
    "ORPHAN": ("client", "server"),
    "PARAM_PUSH": ("client", "server"),
    "PARAM_PUSH_ACK": ("server", "client"),
}
