"""Seeded concurrency violations (mtlint fixture — parsed, never imported)."""

import threading
import time

EXEC = "EXEC"


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self.items = []

    def a_then_b(self):
        with self._lock:
            with self._cv:  # edge _lock -> _cv
                self.items.append(1)

    def b_then_a(self):
        with self._cv:
            with self._lock:  # MT-C201: edge _cv -> _lock inverts a_then_b
                self.items.append(2)

    def hold_and_sleep(self):
        with self._lock:
            time.sleep(0.1)  # MT-C202: blocking while holding _lock

    def pump(self):
        with self._lock:
            yield EXEC  # MT-C203: parked by the scheduler lock-in-hand

    def nap_via_sched(self):
        # Plain function that re-enters the cooperative scheduler; fine
        # on its own, poison when called with a native lock held.
        self.sched.wait()

    def hold_and_greet(self):
        with self._lock:
            self.nap_via_sched()  # MT-Y803: yields via helper, lock held

    def slow_flush(self):
        time.sleep(0.1)

    def hold_and_flush(self):
        with self._lock:
            self.slow_flush()  # MT-C202: blocks one helper down
