"""Seeded concurrency violations (mtlint fixture — parsed, never imported)."""

import threading
import time

EXEC = "EXEC"


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self.items = []

    def a_then_b(self):
        with self._lock:
            with self._cv:  # edge _lock -> _cv
                self.items.append(1)

    def b_then_a(self):
        with self._cv:
            with self._lock:  # MT-C201: edge _cv -> _lock inverts a_then_b
                self.items.append(2)

    def hold_and_sleep(self):
        with self._lock:
            time.sleep(0.1)  # MT-C202: blocking while holding _lock

    def pump(self):
        with self._lock:
            yield EXEC  # MT-C203: parked by the scheduler lock-in-hand
