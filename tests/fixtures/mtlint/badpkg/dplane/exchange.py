"""Seeded dplane hot-path violations (mtlint fixture — parsed, never
imported): host transfers inside device-resident apply/exchange paths."""

import jax
import jax.numpy as jnp
import numpy as np


def apply_update(param, grad, state):
    host = np.asarray(grad)  # MT-J311: host materialization on apply path
    return param + jnp.asarray(host), state


def pull_exchange(slot):
    out = slot.param
    out.block_until_ready()  # MT-J312: device barrier on the hot path
    return out


def sync_round(plane, update):
    loss = update[0].item()  # MT-J311: scalar host pull per op
    jax.device_get(update)  # MT-J311: whole-array host pull
    return loss


def snapshot_host(slot):
    # Exempt by name: the one sanctioned d2h (per-version cache).
    return np.asarray(slot.param)


def timing_probe(x):
    # Exempt by name: timing code may fence.
    x.block_until_ready()
    return x
