"""Seeded donation-seam violations under dplane/ (mtlint fixture —
parsed, never imported).  The rel-path suffix ``dplane/hbm.py`` makes
the hbm-seed-owned and hbm-snapshot-materialize disciplines apply."""


class HbmSlot:
    def __init__(self, n, config):
        self.config = config
        self.version = 0

    def seed(self, value):
        # MT-D903: place_flat aliases host memory; the declared owned
        # path wraps it in device_copy before it can be donated.
        self.param = place_flat(value, self.config)

    def snapshot_host(self):
        # MT-D902: caches the bare donated buffer instead of
        # materializing it — the next apply donates it away.
        self._snap = (self.version, self.param)
        return self._snap[1]
