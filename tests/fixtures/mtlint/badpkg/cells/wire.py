"""Seeded XOR-kernel ownership violation (mtlint fixture — parsed,
never imported).  The rel-path suffix ``cells/wire.py`` puts the
cells-xor-owned-out sink in scope."""

import numpy as np


def bad_delta(pool, a, b, scratch):
    # MT-D901 (cells-xor-owned-out): the kernel output aliases borrowed
    # storage instead of a fresh owned buffer.
    out = np.frombuffer(scratch, np.uint8)
    pool.xor_sync(a, b, out)
    return out
