"""Seeded event-loop callbacks (mtlint fixture — parsed, never run)."""

import time


class BadLoop:
    def _el_on_readable(self, conn):
        # MT-P203: raw blocking recv inside a selector-dispatch callback.
        data = conn.sock.recv(65536)
        # MT-P203: sleeping the loop thread stalls every peer at once.
        time.sleep(0.01)
        return data

    def _el_on_writable(self, conn, payload):
        # MT-P203: sendall blocks the whole loop on one peer's backpressure.
        conn.sock.sendall(payload)

    def _pump_once(self, conn):
        # Not an _el_* callback itself — the local scan never saw this.
        # MT-P203 (interprocedural): raw recv one helper below _el_on_timer.
        conn.sock.recv(64)

    def _el_on_timer(self):
        self._pump_once(self._conn)
