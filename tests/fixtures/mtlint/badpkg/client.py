"""Seeded client role (mtlint fixture — parsed, never imported)."""

import tags
from aio import aio_recv, aio_send


def send_ping(transport, live):
    # MT-P102: the server role has no recv for PING.
    yield from aio_send(transport, b"", 0, tags.PING, live=live)


def push_grad(transport, grad):
    # MT-P103: GRAD is a write tag (GRAD_ACK exists) but the ack tail
    # is never received here.
    yield from aio_send(transport, grad, 0, tags.GRAD)


def fetch(transport):
    # MT-P104: blocks on REPLY before sending REQ, while the server
    # sends REPLY only after receiving REQ.
    out = yield from aio_recv(transport, 0, tags.REPLY)
    yield from aio_send(transport, b"", 0, tags.REQ)
    return out


def emit_rogue(transport, live, deadline):
    # MT-P501/MT-P502 pairing-table seed: ROGUE flows client -> server
    # (so MT-P101/P102 stay quiet) but is registered nowhere.
    yield from aio_send(transport, b"", 0, tags.ROGUE, live=live,
                        deadline=deadline)
