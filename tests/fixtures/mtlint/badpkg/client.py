"""Seeded client role (mtlint fixture — parsed, never imported)."""

import tags
from aio import aio_recv, aio_send


def send_ping(transport, live):
    # MT-P102: the server role has no recv for PING.
    yield from aio_send(transport, b"", 0, tags.PING, live=live)


def push_grad(transport, grad):
    # MT-P103: GRAD is a write tag (GRAD_ACK exists) but the ack tail
    # is never received here.
    yield from aio_send(transport, grad, 0, tags.GRAD)


def fetch(transport):
    # MT-P104: blocks on REPLY before sending REQ, while the server
    # sends REPLY only after receiving REQ.
    out = yield from aio_recv(transport, 0, tags.REPLY)
    yield from aio_send(transport, b"", 0, tags.REQ)
    return out


def emit_rogue(transport, live, deadline):
    # MT-P501/MT-P502 pairing-table seed: ROGUE flows client -> server
    # (so MT-P101/P102 stay quiet) but is registered nowhere.
    yield from aio_send(transport, b"", 0, tags.ROGUE, live=live,
                        deadline=deadline)


def _post_push(transport, frame, deadline):
    # MT-P103 (interprocedural): a helper's naked PARAM_PUSH send whose
    # only caller never observes the PARAM_PUSH_ACK tail — one level of
    # call following must not excuse an ack nobody drains.
    yield from aio_send(transport, frame, 0, tags.PARAM_PUSH,
                        deadline=deadline)


def push_params(transport, frames, deadline):
    for frame in frames:
        yield from _post_push(transport, frame, deadline)


def finalize_push(transport, deadline):
    # Pairs the ack channel for MT-P102 without vouching for _post_push
    # (it never calls the helper).
    yield from aio_recv(transport, 0, tags.PARAM_PUSH_ACK,
                        deadline=deadline)
