"""Seeded worker-pool wait violations (mtlint fixture — parsed, never
imported)."""

import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self.job = None

    def hold_and_collect(self):
        with self._lock:
            self.job.result()  # MT-C204: blocking pool wait, lock held

    def _drain_job(self):
        self.job.result()

    def hold_and_drain(self):
        with self._lock:
            self._drain_job()  # MT-C204: blocks one helper down
