"""Seeded JAX hot-path violations (mtlint fixture — parsed, never imported)."""

import jax
import jax.numpy as jnp


@jax.jit
def bad_step(w, g):
    lr = float(g[0])  # MT-J301: host sync on a traced value
    if jnp.any(g > 0):  # MT-J302: Python branch on a traced expression
        w = w - lr * g
    return w


def update(w, g):
    return w - 0.1 * g


apply_update = jax.jit(update)  # MT-J303: update-shaped, no donate_argnums
