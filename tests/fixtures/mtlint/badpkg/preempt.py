"""Seeded MT-P204 violations: a SIGTERM handler that does real work.

Every call in the handler body below is a seeded finding: taking a lock
(the interrupted frame may hold it), allocating, and a transport send.
"""

import signal
import threading
import time

import numpy as np

_lock = threading.Lock()
transport = None


def on_preempt(signum, frame):
    _lock.acquire()  # seeded MT-P204: lock in a signal handler
    staging = np.zeros(1024)  # seeded MT-P204: allocation
    transport.send(staging, 0, 2)  # seeded MT-P204: blocking transport call
    time.sleep(0.01)  # seeded MT-P204: blocking sleep


signal.signal(signal.SIGTERM, on_preempt)
