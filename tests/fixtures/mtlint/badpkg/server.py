"""Seeded server role (mtlint fixture — parsed, never imported)."""

import tags
from aio import aio_recv, aio_send


def serve_grad(transport, buf):
    # Correct write path: recv GRAD, send the GRAD_ACK tail.
    got = yield from aio_recv(transport, 1, tags.GRAD, out=buf)
    yield from aio_send(transport, b"", 1, tags.GRAD_ACK)
    return got


def serve_req(transport):
    # Half of the seeded MT-P104 cycle: REPLY only after REQ.
    yield from aio_recv(transport, 1, tags.REQ)
    yield from aio_send(transport, b"", 1, tags.REPLY)


def drain(transport):
    # MT-P202: blocking transport convenience — unbounded busy-wait.
    return transport.recv(1, tags.GRAD)
