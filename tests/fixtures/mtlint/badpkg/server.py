"""Seeded server role (mtlint fixture — parsed, never imported)."""

import tags
from aio import aio_recv, aio_send


def serve_grad(transport, buf):
    # Correct write path: recv GRAD, send the GRAD_ACK tail.
    got = yield from aio_recv(transport, 1, tags.GRAD, out=buf)
    yield from aio_send(transport, b"", 1, tags.GRAD_ACK)
    return got


def serve_req(transport):
    # Half of the seeded MT-P104 cycle: REPLY only after REQ.
    yield from aio_recv(transport, 1, tags.REQ)
    yield from aio_send(transport, b"", 1, tags.REPLY)


def drain(transport):
    # MT-P202: blocking transport convenience — unbounded busy-wait.
    return transport.recv(1, tags.GRAD)


def timing_report():
    import time

    tw = time.time()  # MT-O401: wall clock read in a role file
    t0 = time.monotonic()
    work = sum(range(1000))
    elapsed = time.monotonic() - t0  # MT-O401: hand-rolled elapsed timing
    print("served in", elapsed, work, tw)  # MT-O402: print() reporting
    return elapsed


def drain_rogue(transport, live, gone):
    # Peer side of the MT-P501/MT-P502 seed (keeps the channel paired).
    yield from aio_recv(transport, 1, tags.ROGUE, live=live, abort=gone)


def report_widgets(registry):
    # MT-O403 seed: mpit_rogue_widgets_total is instantiated but absent
    # from this fixture's docs/OBSERVABILITY.md catalog; the documented
    # mpit_good_widgets_total must stay silent.
    registry.counter("mpit_good_widgets_total").inc()
    registry.counter("mpit_rogue_widgets_total").inc()


def trace_phases(span):
    # MT-O404 seed: rogue_phase is absent from this fixture's
    # docs/OBSERVABILITY.md phase taxonomy; good_phase is documented
    # there and must stay silent.
    span.mark("good_phase")
    span.mark("rogue_phase")


def _ack_push(transport, peer, live, gone):
    yield from aio_send(transport, b"", peer, tags.PARAM_PUSH_ACK,
                        live=live, abort=gone)


def absorb_push(transport, buf, live, gone):
    # Correct helper-split server write path: the ack rides _ack_push —
    # the interprocedural scan must stay quiet here.
    got = yield from aio_recv(transport, 1, tags.PARAM_PUSH, out=buf,
                              live=live, abort=gone)
    yield from _ack_push(transport, 1, live, gone)
    return got
