"""Seeded pooled-decode ownership violations (mtlint fixture — parsed,
never imported).  The rel-path suffix ``ps/client.py`` puts the pooled
chunked-read disciplines in scope."""

import numpy as np


class Client:
    def _chunked_read(self, body, out, lo, hi):
        # MT-D901 (pool-client-decode-owned): the reused rx frame view
        # goes to the pool without an owning snapshot.
        job = self.pool.submit_decode(
            self.codec, np.frombuffer(body, np.uint8), out[lo:hi])
        # MT-D903 (pool-client-decode-owned-copy): a stray copy outside
        # the submit boundary.
        spare = np.array(body)
        return job, spare
