"""Seeded yield-atomicity + ownership violations (mtlint fixture —
parsed, never imported).  The rel-path suffix ``ps/server.py`` makes
the declared disciplines in mpit_tpu.analysis.disciplines apply here:
the read-gate window, the device-plane single-writer set and the
chunk-apply donation seam."""

import numpy as np

EXEC = "EXEC"


class PS:
    def _read_gate(self):
        if self.lag > self.bound:
            return None
        return self.version

    def _dispatch_read(self, req):
        gate = self._read_gate()
        # MT-Y801: scheduler yield inside the declared read-gate window.
        yield EXEC
        self.serve(gate, req)

    def steal_ticket(self):
        # MT-Y802: pops the device plane outside the declared writer set.
        return self._plane.pop()

    def bad_apply(self, codec, blob, lo):
        # MT-D901: a frombuffer view of the receive ring reaches the
        # donated chunk apply.
        self._hbm.apply_wire_chunk(codec, np.frombuffer(blob, np.float32), lo)

    def lazy_apply(self, codec, grad, lo):
        # MT-D903: ownership of a bare parameter cannot be proven at
        # the declared seam.
        self._hbm.apply_wire_chunk(codec, grad, lo)
