"""Seeded yield-atomicity + ownership violations (mtlint fixture —
parsed, never imported).  The rel-path suffix ``ps/server.py`` makes
the declared disciplines in mpit_tpu.analysis.disciplines apply here:
the read-gate window, the device-plane single-writer set and the
chunk-apply donation seam."""

import numpy as np

EXEC = "EXEC"


class PS:
    def _read_gate(self):
        if self.lag > self.bound:
            return None
        return self.version

    def _dispatch_read(self, req):
        gate = self._read_gate()
        # MT-Y801: scheduler yield inside the declared read-gate window.
        yield EXEC
        self.serve(gate, req)

    def steal_ticket(self):
        # MT-Y802: pops the device plane outside the declared writer set.
        return self._plane.pop()

    def bad_apply(self, codec, blob, lo):
        # MT-D901: a frombuffer view of the receive ring reaches the
        # donated chunk apply.
        self._hbm.apply_wire_chunk(codec, np.frombuffer(blob, np.float32), lo)

    def lazy_apply(self, codec, grad, lo):
        # MT-D903: ownership of a bare parameter cannot be proven at
        # the declared seam.
        self._hbm.apply_wire_chunk(codec, grad, lo)

    def _snapshot_wire(self):
        # MT-C204: blocking pool wait inside the declared yield-free
        # read-path window (ps-read-path-helpers).
        self.job.result()
        return self._wire

    def _recv_param_chunked(self, codec, asm, lo, hi, blob):
        # MT-D901 (pool-server-scatter-owned): a frombuffer view of the
        # reused receive buffer submitted to the worker pool.
        self.pool.submit_scatter(
            codec, asm, self.size, lo, hi, np.frombuffer(blob, np.uint8))
        # MT-D903 (pool-server-scatter-owned-copy): a stray owning copy
        # outside the submit boundary.
        return np.array(blob)
