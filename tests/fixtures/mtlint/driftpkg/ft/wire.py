"""Seeded schema-drift wire module (mtlint fixture — parsed, never
imported).  Every deviation from analysis/schema.py's registry here is
deliberate and pinned by tests/test_analysis.py."""

import numpy as np

HDR_BYTES = 24  # MT-S601: schema says 16 — pack/unpack widths diverge
HDR_STALE_BYTES = 24
FLAG_FRAMED = 1
FLAG_HEARTBEAT = 2
FLAG_STALENESS = 4
FLAG_TIMING = 8
FLAG_READONLY = 16
FLAG_SUBSCRIBE = 32
FLAG_CHUNKED = 64
TIMING_TAIL_WORDS = 3
TIMING_TAIL_BYTES = 8 * TIMING_TAIL_WORDS
ACK_TIMING_WORDS = 5
CHUNK_HDR_BYTES = 32
CHUNK_ACK_WORDS = 3
CHUNK_ACK_TIMING_WORDS = CHUNK_ACK_WORDS + TIMING_TAIL_WORDS
CHUNK_REPLY_WORDS = 5
CHUNK_BLOCK = 1024
FLAG_ROGUE = 128  # MT-S601: not in the schema registry
# MT-S601 (missing): HDR_STALE... actually the registry also wants every
# declared constant present — init_v3 below drifts instead.


def pack_header(buf, epoch, seq):
    buf[:HDR_BYTES].view(np.int64)[:] = (epoch, seq)


def unpack_header(buf):
    hdr = buf[:HDR_BYTES].view(np.int64)
    return int(hdr[0]), int(hdr[1])


def header_frame(epoch, seq):
    return np.asarray([epoch, seq], dtype=np.int64)


def timed_frame(epoch, seq, t_us):
    return np.asarray([epoch, seq, t_us], dtype=np.int64)


def init_v3(offset, size, codec_id, epoch, flags, extra):
    # MT-S602: six words where the schema layout says five — the v3
    # announcement grew a field only one side knows about.
    return np.asarray([offset, size, codec_id, epoch, flags, extra],
                      dtype=np.int64)


def init_v5(offset, size, codec_id, epoch, flags, chunk_elems):
    return np.asarray([offset, size, codec_id, epoch, flags, chunk_elems],
                      dtype=np.int64)


def pack_reply_stamps(buf, base, t_tx, t_recv, t_ack):
    buf[base:base + TIMING_TAIL_BYTES].view(np.int64)[:] = (
        t_tx, t_recv, t_ack)


def unpack_reply_stamps(buf, base):
    tail = buf[base:base + TIMING_TAIL_BYTES].view(np.int64)
    return int(tail[0]), int(tail[1]), int(tail[2])


def pack_chunk_header(buf, epoch, seq, idx, count):
    buf[:CHUNK_HDR_BYTES].view(np.int64)[:] = (epoch, seq, idx, count)


def unpack_chunk_header(buf):
    hdr = buf[:CHUNK_HDR_BYTES].view(np.int64)
    return int(hdr[0]), int(hdr[1]), int(hdr[2]), int(hdr[3])


def pack_chunk_reply(buf, epoch, seq, idx, count, version):
    buf[:8 * CHUNK_REPLY_WORDS].view(np.int64)[:] = (
        epoch, seq, idx, count, version)


def unpack_chunk_reply(buf):
    hdr = buf[:8 * CHUNK_REPLY_WORDS].view(np.int64)
    return (int(hdr[0]), int(hdr[1]), int(hdr[2]), int(hdr[3]),
            int(hdr[4]))


def chunk_ack_frame(epoch, seq, idx):
    return np.asarray([epoch, seq, idx], dtype=np.int64)


def rogue_frame(a, b, c, d, e, f, g, h):
    # MT-S602: an eight-word struct literal registered nowhere — a frame
    # layout that bypassed the schema entirely.
    return np.asarray([a, b, c, d, e, f, g, h], dtype=np.int64)
