"""Seeded tag-registry drift (mtlint fixture — parsed, never imported).
Deviations from analysis/schema.py TAGS are deliberate."""

INIT = 1
GRAD = 2
GRAD_ACK = 3
PARAM_REQ = 4
PARAM = 5
PARAM_PUSH = 6
PARAM_PUSH_ACK = 7
STOP = 8
HEARTBEAT = 9
MAP_UPDATE = 10
SHARD_PULL = 11
SHARD_STATE = 12
HEARTBEAT_ECHO = 13
DIFF = 14
DIFF_REQ = 15
REDUCE = 18  # MT-S603: schema says 16 — the id itself drifted
REDUCE_ACK = 17
SIDEBAND = 19  # MT-S603: a tag the schema registry does not declare

EMPTY = b""

TAG_PAIRS = {
    "INIT": ("client", "server"),
    "GRAD": ("client", "server"),
    "GRAD_ACK": ("server", "client"),
    "PARAM_REQ": ("client", "server"),
    "PARAM": ("server", "client"),
    "PARAM_PUSH": ("client", "server"),
    "PARAM_PUSH_ACK": ("server", "client"),
    "STOP": ("client", "server|controller"),
    "HEARTBEAT": ("client|server", "server|controller"),
    "MAP_UPDATE": ("controller|server", "server|client|controller"),
    "SHARD_PULL": ("server", "server"),
    "SHARD_STATE": ("server", "server"),
    "HEARTBEAT_ECHO": ("server", "client"),
    "DIFF": ("server", "server"),  # MT-S603: schema says (server, cell)
    "DIFF_REQ": ("cell", "server"),
    "REDUCE": ("client", "client"),
    "REDUCE_ACK": ("client", "client"),
    # MT-S603: SIDEBAND has a TAG_PAIRS row but no schema TagSpec
    "SIDEBAND": ("client", "server"),
}
