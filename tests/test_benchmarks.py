"""Smoke-run each benchmark script at tiny sizes (subprocess, CPU) and
check the JSON contract the driver/judge consume — the reverse of the
reference, whose "tests" were its benchmarks (SURVEY.md §4); here the
benchmarks get tests.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks")


def run_bench(script, extra_env, timeout=420):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # 4 mesh devices + pool headroom (docs/xla_cpu_rendezvous_abort.md)
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        MPIT_MESH_DEVICES="4",
        MPIT_BENCH_ROUNDS="2",
        **extra_env,
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(BENCH, script)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, proc.stdout
    return [json.loads(l) for l in lines]


def test_ptest_ici_and_shm():
    results = run_bench(
        "ptest.py",
        {"MPIT_BENCH_MB": "1", "MPIT_BENCH_SERVERS": "1",
         "MPIT_BENCH_CLIENTS": "1"},
    )
    by_metric = {r["metric"]: r for r in results}
    ici = by_metric["ps_pushpull_bandwidth_ici"]
    assert ici["value"] > 0 and ici["unit"] == "MB/s" and ici["devices"] == 4
    shm = by_metric["ps_pushpull_bandwidth_shm"]
    assert shm["value"] > 0 and shm["clients"] == 1


def test_ptest2_skewed_soak():
    (r,) = run_bench(
        "ptest2.py",
        {"MPIT_BENCH_MB": "1", "MPIT_BENCH_CLIENTS": "2",
         "MPIT_BENCH_SKEW": "0.01"},
    )
    assert r["metric"] == "ps_soak_bandwidth_skewed"
    assert r["value"] > 0 and r["clients"] == 2
    assert r["fast_slow_ratio"] >= 1.0


def test_testreduceall():
    (r,) = run_bench("testreduceall.py", {"MEGS": "1"})
    assert r["metric"] == "allreduce_ms_per_round"
    assert r["value"] > 0 and r["devices"] == 4
    assert r["payload_mb"] == 1.0


def test_testreduceall_shm_mode():
    """Host-transport leg: ring allreduce between real processes over the
    shm transport (the literal test/testreduceall.lua shape)."""
    (r,) = run_bench(
        "testreduceall.py",
        {"MEGS": "1", "MPIT_BENCH_MODE": "shm", "MPIT_BENCH_RANKS": "3"},
    )
    assert r["metric"] == "host_allreduce_bandwidth_shm"
    # 3 ranks: the smallest NON-degenerate ring (a 2-rank ring always
    # talks to the same peer, hiding neighbor-rotation bugs).
    assert r["value"] > 0 and r["ranks"] == 3
    assert r["ms_per_round"] > 0
