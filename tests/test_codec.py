"""Wire-codec tests: registry/negotiation surface, round-trip property
bounds per codec, int8 error-feedback behavior, and the zero-copy
send-buffer rule (comm/transport.py as_bytes_view regression).
"""

import numpy as np
import pytest

from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm.transport import as_bytes_view

SIZES = [1, 7, 1023, 1024, 1025, 4096, 5000, codec_mod._TILE * 2 + 511]


def rnd(n, seed=0, scale=3.0):
    return (scale * np.random.default_rng(seed).standard_normal(n)).astype(
        np.float32
    )


class TestRegistry:
    def test_default_is_none(self, monkeypatch):
        monkeypatch.delenv(codec_mod.ENV, raising=False)
        assert codec_mod.get().name == "none"
        assert codec_mod.get("").name == "none"

    def test_env_selection(self, monkeypatch):
        monkeypatch.setenv(codec_mod.ENV, "int8")
        assert codec_mod.get().name == "int8"
        # an explicit name beats the env
        assert codec_mod.get("bf16").name == "bf16"

    def test_unknown_name_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown PS codec"):
            codec_mod.get("zstd")

    def test_unknown_wire_id_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown codec wire id"):
            codec_mod.by_wire_id(99)

    def test_wire_ids_are_stable(self):
        # Wire ids are protocol constants (docs/PROTOCOL.md) — changing
        # one breaks INIT interop with every deployed peer.
        assert {c: codec_mod.get(c).wire_id
                for c in codec_mod.names()} == {
            "none": 0, "bf16": 1, "int8": 2}


class TestRoundTrip:
    @pytest.mark.parametrize("size", SIZES)
    def test_none_exact(self, size):
        c = codec_mod.get("none")
        x = rnd(size)
        wire = np.zeros(c.wire_nbytes(size), np.uint8)
        c.encode_into(x, wire)
        out = np.empty_like(x)
        c.decode_into(wire, out)
        np.testing.assert_array_equal(out, x)

    @pytest.mark.parametrize("size", SIZES)
    def test_bf16_truncation_bound(self, size):
        # bf16 keeps 7 explicit mantissa bits; truncation (round toward
        # zero) error is < one ulp = 2^-7 relative, element-wise.
        c = codec_mod.get("bf16")
        x = rnd(size, seed=1)
        wire = np.zeros(c.wire_nbytes(size), np.uint8)
        c.encode_into(x, wire)
        out = np.empty_like(x)
        c.decode_into(wire, out)
        assert np.all(np.abs(out - x) <= np.abs(x) * 2.0**-7 + 1e-30)
        # truncation, not rounding: magnitude never grows
        assert np.all(np.abs(out) <= np.abs(x))

    @pytest.mark.parametrize("size", SIZES)
    def test_int8_per_block_bound(self, size):
        # Each element's error is bounded by its OWN block's scale/2 =
        # absmax/254 — the per-block guarantee whole-tensor scaling
        # cannot give.
        c = codec_mod.get("int8")
        B = codec_mod.BLOCK
        x = rnd(size, seed=2)
        if size > B:  # make block magnitudes wildly different
            x[:B] *= 1000.0
        wire = np.zeros(c.wire_nbytes(size), np.uint8)
        c.encode_into(x, wire)
        out = np.empty_like(x)
        c.decode_into(wire, out)
        err = np.abs(out - x)
        for lo in range(0, size, B):
            blk = slice(lo, min(lo + B, size))
            bound = np.abs(x[blk]).max() / 254.0
            assert err[blk].max() <= bound * (1 + 1e-5) + 1e-30

    @pytest.mark.parametrize("name", ["none", "bf16", "int8"])
    def test_zero_vector_round_trips(self, name):
        c = codec_mod.get(name)
        x = np.zeros(2048, np.float32)
        wire = np.zeros(c.wire_nbytes(2048), np.uint8)
        c.encode_into(x, wire)
        out = np.full(2048, 7.0, np.float32)
        c.decode_into(wire, out)
        np.testing.assert_array_equal(out, 0.0)

    @pytest.mark.parametrize("name", ["none", "bf16", "int8"])
    def test_split_wire_matches_host_decode(self, name):
        """decode_parts (the server's fused jit path) must equal
        decode_into (the client's host path) bit for bit."""
        import jax.numpy as jnp

        c = codec_mod.get(name)
        size = 3 * codec_mod.BLOCK + 77
        x = rnd(size, seed=3)
        wire = np.zeros(c.wire_nbytes(size), np.uint8)
        c.encode_into(x, wire)
        host = np.empty_like(x)
        c.decode_into(wire, host)
        parts = [jnp.asarray(v) for v in c.split_wire(wire, size)]
        fused = np.asarray(c.decode_parts(parts, size))
        np.testing.assert_array_equal(fused, host)


class TestErrorFeedback:
    def test_residual_drains_to_zero_on_constant_grads(self):
        # A constant vector sits exactly on the quantization grid (every
        # element IS its block's absmax), so one EF step representing it
        # exactly leaves nothing behind.
        c = codec_mod.get("int8")
        g = np.full(4096, 0.37, np.float32)
        r = np.full(4096, 0.123, np.float32)  # start dirty
        wire = np.zeros(c.wire_nbytes(4096), np.uint8)
        for _ in range(2):
            c.encode_into(g, wire, residual=r)
        assert np.abs(r).max() == 0.0

    def test_residual_is_exact_quantization_error(self):
        c = codec_mod.get("int8")
        x = rnd(5000, seed=4)
        r = np.zeros_like(x)
        wire = np.zeros(c.wire_nbytes(5000), np.uint8)
        c.encode_into(x, wire, residual=r)
        out = np.empty_like(x)
        c.decode_into(wire, out)
        np.testing.assert_allclose(r, x - out, atol=1e-6)

    def test_cumulative_feedback_tracks_true_sum(self):
        # EF invariant: sum of decoded frames = sum of true grads minus
        # the current residual — compression error never accumulates.
        c = codec_mod.get("int8")
        size = 2048
        r = np.zeros(size, np.float32)
        wire = np.zeros(c.wire_nbytes(size), np.uint8)
        true_sum = np.zeros(size, np.float64)
        dec_sum = np.zeros(size, np.float64)
        out = np.empty(size, np.float32)
        for step in range(20):
            g = rnd(size, seed=10 + step)
            true_sum += g
            c.encode_into(g, wire, residual=r)
            c.decode_into(wire, out)
            dec_sum += out
        np.testing.assert_allclose(dec_sum + r, true_sum, atol=2e-3)
        # and the residual itself stays bounded by one quantization step
        assert np.abs(r).max() < 0.2

    def test_no_residual_matches_zero_residual(self):
        c = codec_mod.get("int8")
        x = rnd(3000, seed=5)
        w1 = np.zeros(c.wire_nbytes(3000), np.uint8)
        w2 = np.zeros_like(w1)
        c.encode_into(x, w1)
        c.encode_into(x, w2, residual=np.zeros_like(x))
        assert bytes(w1) == bytes(w2)


class TestNativeParity:
    """The native kernels (comm/native/transport.cpp mt_codec_*) must be
    bit-identical to the numpy reference paths — build.py pins
    -ffp-contract=off precisely so this holds.  Skipped where the native
    lib cannot build (no g++); the numpy path is then the only path."""

    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("name", ["bf16", "int8"])
    def test_native_matches_numpy_oracle(self, name, size, monkeypatch):
        if codec_mod._native() is None:
            pytest.skip("native codec kernels unavailable")
        c = codec_mod.get(name)
        x = rnd(size, seed=6)
        use_res = c.uses_residual
        rn = np.full(size, 0.01, np.float32)
        rv = rn.copy()
        wn = np.zeros(c.wire_nbytes(size), np.uint8)
        wv = np.zeros_like(wn)
        ov = np.empty(size, np.float32)
        c.encode_into(x, wv, residual=rv if use_res else None)  # native
        c.decode_into(wv, ov)
        monkeypatch.setattr(codec_mod, "_native_lib", False)  # numpy path
        assert codec_mod._native() is None
        c.encode_into(x, wn, residual=rn if use_res else None)
        on = np.empty(size, np.float32)
        c.decode_into(wv, on)  # numpy decode of the native frame
        assert bytes(wn) == bytes(wv)
        np.testing.assert_array_equal(on, ov)
        if use_res:
            np.testing.assert_array_equal(rn, rv)

    def test_env_kill_switch(self, monkeypatch):
        import os

        monkeypatch.setattr(codec_mod, "_native_lib", None)
        monkeypatch.setenv(codec_mod._NATIVE_ENV, "0")
        assert codec_mod._native() is None
        monkeypatch.setattr(codec_mod, "_native_lib", None)
        monkeypatch.delenv(codec_mod._NATIVE_ENV)
        # cache reset: default path retries the build lazily
        codec_mod._native()
        monkeypatch.setattr(codec_mod, "_native_lib", None)


class TestZeroCopySendRule:
    """Satellite regression: as_bytes_view used to silently
    ascontiguousarray-copy non-contiguous send buffers, detaching the
    transport from the caller's buffer under the documented liveness
    contract."""

    def test_non_contiguous_send_buffer_raises(self):
        arr = np.arange(16, dtype=np.float32)[::2]
        assert not arr.flags["C_CONTIGUOUS"]
        with pytest.raises(ValueError, match="C-contiguous"):
            as_bytes_view(arr)

    def test_contiguous_is_zero_copy(self):
        arr = np.arange(4, dtype=np.float32)
        view = as_bytes_view(arr)
        arr[0] = 42.0  # the view must alias the caller's memory
        assert np.frombuffer(view, np.float32)[0] == 42.0

    def test_bytes_and_memoryview_still_accepted(self):
        assert bytes(as_bytes_view(b"abc")) == b"abc"
        assert bytes(as_bytes_view(memoryview(b"xy"))) == b"xy"

    def test_transport_isend_propagates_the_error(self):
        from mpit_tpu.comm.local import LocalRouter

        router = LocalRouter(2)
        a = router.endpoint(0)
        handle = a.isend(np.arange(16, dtype=np.float32)[::2], 1, 5)
        with pytest.raises(ValueError, match="C-contiguous"):
            while not a.test(handle):
                pass
