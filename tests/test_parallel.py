"""Mesh/collective layer tests on the 8-virtual-device CPU platform.

Invariant-based (SURVEY.md §7 "deterministic tests of nondeterministic
algorithms"): shard bookkeeping exactness, elastic algebra vs. a NumPy
sequential simulator, sync-DP equivalence to single-device training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import MnistMLP, flatten_module
from mpit_tpu.optim.msgd import MSGDConfig
from mpit_tpu.parallel import (
    MeshEASGD,
    SyncDataParallel,
    allreduce_mean,
    make_mesh,
    ps_pull,
    ps_push,
    ps_pushpull,
    ring_shift,
)


@pytest.fixture(scope="module")
def mesh():
    from mpit_tpu.utils.platform import default_devices

    assert len(default_devices()) == 8, "conftest must provide 8 mesh devices"
    return make_mesh(dp=4, shard=2)


def test_make_mesh_factoring():
    m = make_mesh()
    assert m.shape["dp"] * m.shape["shard"] == 8  # capped by MPIT_MESH_DEVICES
    with pytest.raises(ValueError):
        make_mesh(dp=3)


def test_ps_pull_concatenates_shards(mesh):
    x = jnp.arange(16.0)
    pulled = ps_pull(mesh)(x)
    np.testing.assert_allclose(np.asarray(pulled), np.arange(16.0))


def test_ps_push_delivers_exact_slices(mesh):
    # A replicated grad must arrive at each shard owner exactly once —
    # no shard-count-dependent scaling.
    g = jnp.arange(16.0)
    out = ps_push(mesh)(g)
    np.testing.assert_allclose(np.asarray(out), np.arange(16.0))


def test_ps_push_reduces_worker_stack(mesh):
    # Per-worker grads summed over dp, then sliced per shard owner.
    n_dp = mesh.shape["dp"]
    g = jnp.broadcast_to(jnp.arange(16.0), (n_dp, 16))
    out = ps_push(mesh, reduce_axis="dp")(g)
    np.testing.assert_allclose(np.asarray(out), n_dp * np.arange(16.0))


def test_ps_pushpull_round_plain_add(mesh):
    # One full PS round with the plain-add server rule (pserver.lua:83):
    # params move by exactly the pushed gradient.
    p = jnp.zeros((16,))
    g = jnp.arange(16.0)
    full, p_shard = ps_pushpull(mesh, lambda ps, gs: ps + gs)(p, g)
    np.testing.assert_allclose(np.asarray(full), np.arange(16.0))


def test_ring_shift_rotates_blocks(mesh):
    x = jnp.arange(8.0)  # 2 shard blocks of 4
    y = ring_shift(mesh, "shard")(x)
    np.testing.assert_allclose(np.asarray(y), np.r_[np.arange(4.0) + 4, np.arange(4.0)])


def test_allreduce_mean(mesh):
    x = jnp.arange(4.0).repeat(2)  # (8,) -> rows 0..3 over dp
    y = allreduce_mean(mesh)(jnp.arange(8.0))
    got = np.asarray(y).reshape(4, 2)
    np.testing.assert_allclose(got, np.tile(np.mean(np.arange(8.0).reshape(4, 2), 0), (4, 1)))


def _quadratic_vgf(target):
    def vgf(w, xb, yb):  # ignores batch content; deterministic quadratic
        loss = 0.5 * jnp.sum((w - target) ** 2)
        return loss, w - target
    return vgf


class TestMeshEASGD:
    def test_elastic_algebra_matches_simulator(self, mesh):
        """One sync step == the NumPy sequential simulation of p simultaneous
        elastic pushes (reference optim-eamsgd.lua:58-66 semantics)."""
        P_ = 16
        n_dp = mesh.shape["dp"]
        target = jnp.linspace(-1, 1, P_)
        cfg = MSGDConfig(lr=0.1, mom=0.0)
        tr = MeshEASGD(mesh, _quadratic_vgf(target), cfg, mva=0.9 / n_dp, su=1)
        w0 = jnp.ones((P_,))
        state = tr.init(w0)
        xb = jnp.zeros((n_dp, 2, 1))
        yb = jnp.zeros((n_dp, 2), jnp.int32)
        state, loss = tr.step(state, *tr.shard_batch(xb, yb))

        # simulator
        w = np.ones((n_dp, P_), np.float64)
        center = np.ones(P_, np.float64)
        mva = 0.9 / n_dp
        sug = mva * (w - center)
        center_new = center + sug.sum(0)
        w_local = w - 0.1 * (w - np.asarray(target, np.float64))  # msgd, mom=0
        w_new = w_local - sug

        np.testing.assert_allclose(np.asarray(state["center"]), center_new, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(state["w"]), w_new, rtol=1e-5)

    def test_su_gates_exchange(self, mesh):
        P_ = 16
        n_dp = mesh.shape["dp"]
        cfg = MSGDConfig(lr=0.1)
        tr = MeshEASGD(mesh, _quadratic_vgf(jnp.zeros(P_)), cfg, mva=0.1, su=3)
        state = tr.init(jnp.ones((P_,)))
        xb = jnp.zeros((n_dp, 2, 1)); yb = jnp.zeros((n_dp, 2), jnp.int32)
        batches = tr.shard_batch(xb, yb)
        c0 = np.asarray(state["center"]).copy()
        state, _ = tr.step(state, *batches)   # step 0: sync, but w==center -> no-op
        state, _ = tr.step(state, *batches)   # steps 1,2: local only
        state, _ = tr.step(state, *batches)
        np.testing.assert_array_equal(np.asarray(state["center"]), c0)
        state, _ = tr.step(state, *batches)   # step 3: sync, w has diverged
        c1 = np.asarray(state["center"]).copy()
        assert not np.allclose(c0, c1)
        state, _ = tr.step(state, *batches)   # step 4: local only
        np.testing.assert_array_equal(np.asarray(state["center"]), c1)

    def test_fused_commit_matches_xla(self, mesh):
        """use_fused=True (shard_map'd pallas sweep, retract riding the
        commit on sync rounds) reproduces the plain-XLA trajectory."""
        P_ = 300  # not a tile multiple: exercises the flat-vector padding
        n_dp = mesh.shape["dp"]
        target = jnp.linspace(-1, 1, P_)
        xb = jnp.zeros((n_dp, 2, 1)); yb = jnp.zeros((n_dp, 2), jnp.int32)
        states = {}
        for fused in (False, True):
            cfg = MSGDConfig(lr=0.1, mom=0.6, l2wd=1e-3, lrd=0.01, lrp=1.0,
                             use_fused=fused)
            tr = MeshEASGD(mesh, _quadratic_vgf(target), cfg,
                           mva=0.5 / n_dp, su=2)
            assert tr._use_fused is fused
            state = tr.init(jnp.ones((P_,)))
            batches = tr.shard_batch(xb, yb)
            for _ in range(5):
                state, _ = tr.step(state, *batches)
            states[fused] = state
        for key in ("w", "vt", "center"):
            np.testing.assert_allclose(
                np.asarray(states[True][key]), np.asarray(states[False][key]),
                atol=1e-6, err_msg=key,
            )

    def test_workers_converge_to_target(self, mesh):
        P_ = 16
        n_dp = mesh.shape["dp"]
        target = jnp.linspace(0.5, 1.5, P_)
        cfg = MSGDConfig(lr=0.2, mom=0.5)
        tr = MeshEASGD(mesh, _quadratic_vgf(target), cfg, mva=0.9 / n_dp, su=2)
        state = tr.init(jnp.zeros((P_,)))
        xb = jnp.zeros((n_dp, 2, 1)); yb = jnp.zeros((n_dp, 2), jnp.int32)
        batches = tr.shard_batch(xb, yb)
        for _ in range(60):
            state, loss = tr.step(state, *batches)
        np.testing.assert_allclose(
            np.asarray(tr.center_params(state)), np.asarray(target), atol=0.05
        )


class TestSyncDataParallel:
    def test_matches_single_device_msgd(self, mesh):
        """Sharded step == unsharded step: the shardings change placement,
        not math."""
        rng = jax.random.PRNGKey(0)
        module = MnistMLP(hidden=16)
        x = jax.random.normal(rng, (8, 64))
        y = jnp.arange(8) % 10
        flat = flatten_module(module, rng, x[:2])

        def vgf(w, xb, yb):
            def loss_fn(w):
                logp = flat.apply_flat(w, xb)
                return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))
            return jax.value_and_grad(loss_fn)(w)

        cfg = MSGDConfig(lr=0.1, mom=0.9)
        tr = SyncDataParallel(mesh, vgf, cfg)
        state = tr.init(flat.w0)
        xb, yb = tr.shard_batch(x, y)
        for _ in range(3):
            state, loss = tr.step(state, xb, yb)

        # reference: plain jit on one device
        from mpit_tpu.optim.msgd import MSGD
        ref = MSGD(cfg, vgf)
        w = flat.w0
        for _ in range(3):
            w, ref_loss = ref.step(w, x, y)
        np.testing.assert_allclose(np.asarray(state["w"]), np.asarray(w), atol=1e-5)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)

    def test_fused_commit_matches_xla(self, mesh):
        """The shard_map'd fused commit over 1-D shard slices reproduces
        the plain-XLA sync-DP trajectory."""
        rng = jax.random.PRNGKey(1)
        module = MnistMLP(hidden=16)
        x = jax.random.normal(rng, (8, 64))
        y = jnp.arange(8) % 10
        flat = flatten_module(module, rng, x[:2])

        def vgf(w, xb, yb):
            def loss_fn(w):
                logp = flat.apply_flat(w, xb)
                return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))
            return jax.value_and_grad(loss_fn)(w)

        finals = {}
        for fused in (False, True):
            cfg = MSGDConfig(lr=0.1, mom=0.9, l2wd=1e-4, use_fused=fused)
            tr = SyncDataParallel(mesh, vgf, cfg)
            assert tr._use_fused is fused
            state = tr.init(flat.w0)
            xb, yb = tr.shard_batch(x, y)
            for _ in range(3):
                state, _ = tr.step(state, xb, yb)
            finals[fused] = state
        for key in ("w", "vt"):
            np.testing.assert_allclose(
                np.asarray(finals[True][key]), np.asarray(finals[False][key]),
                atol=1e-6, err_msg=key,
            )
