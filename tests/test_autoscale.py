"""Closed-loop autoscaling (ISSUE 11 / docs/OPERATIONS.md) — the
SLO-driven policy engine, the trace-driven traffic generator, and the
wiring that closes the loop.

The determinism contracts under test:

- the **policy is a pure function of the window stream**: replaying a
  synthetic telemetry sequence reproduces the decision sequence
  exactly — hysteresis holds inside the band, cooldown suppresses,
  the flap budget caps oscillation, operator override wins;
- the **traffic generator is bit-reproducible**: the same (spec, seed)
  expands to the identical event schedule, element for element;
- the **samplers** fold exposition snapshots into windowed signals with
  exact counter/bucket-delta arithmetic (the obs/top read path);
- the **flight dumps** the autoscaler writes carry the decision and
  its telemetry window, and the validator rejects ones that don't;
- the **closed loop** executes: a breaching window stream makes a real
  controller widen a real gang (and an idle stream shrink it) with the
  audit trail naming the signal.
"""

import json
import threading

import numpy as np
import pytest

from mpit_tpu import obs
from mpit_tpu.ft.traffic import (
    GRAD,
    JOIN,
    PREEMPT,
    READ,
    STRAGGLE_OFF,
    STRAGGLE_ON,
    Scenario,
    TrafficPhase,
    iter_ticks,
)
from mpit_tpu.obs import top as obs_top
from mpit_tpu.shardctl.autoscale import (
    DOWN,
    HOLD,
    UP,
    AutoscaleConfig,
    AutoscalePolicy,
    Autoscaler,
    HttpSampler,
    RegistrySampler,
    SLOConfig,
    TelemetryWindow,
    window_from_samples,
)


@pytest.fixture
def obs_on():
    obs.configure(enabled=True, reset=True)
    try:
        yield
    finally:
        obs.configure(enabled=None, reset=True)


def cfg(**kw):
    base = dict(
        slo=SLOConfig(p99_ms=10.0),
        window_s=1.0, high_frac=1.0, low_frac=0.5,
        breach_windows=2, idle_windows=3,
        cooldown_s=5.0, settle_s=2.0,
        flap_budget=2, flap_window_s=100.0,
        override_hold_s=10.0, min_servers=1, max_servers=4,
    )
    base.update(kw)
    return AutoscaleConfig(**base)


def w(t, p99=None, busy=0.0, stale=0.0, sendq=0.0, gang=2):
    return TelemetryWindow(t=float(t), p99_ms=p99, busy_ratio=busy,
                           staleness=stale, send_queue=sendq,
                           gang_size=gang)


def run_seq(policy, windows, gang=2):
    """Replay a window list; returns [(action, reason)] — the exact
    sequence the determinism contract pins."""
    out = []
    for window in windows:
        d = policy.decide(window, gang)
        if d.action in (UP, DOWN):
            policy.note_executed(d)  # tests model a successful verb
        out.append((d.action, d.reason))
    return out


# ---------------------------------------------------------------------------
# the pure policy: exact decision sequences


class TestPolicyDecisions:
    def test_breach_debounce_then_up(self):
        p = AutoscalePolicy(cfg())
        seq = run_seq(p, [w(0, p99=20), w(1, p99=20), w(2, p99=20)])
        assert seq == [(HOLD, "breach_pending"), (UP, "slo:p99_ms"),
                       (HOLD, "cooldown")]

    def test_hysteresis_holds_inside_the_band(self):
        """Between low (5ms) and high (10ms) nothing ever fires, and
        the band resets both streaks — one breaching window followed by
        in-band windows never accumulates into an action."""
        p = AutoscalePolicy(cfg())
        seq = run_seq(p, [w(0, p99=20), w(1, p99=7), w(2, p99=20),
                          w(3, p99=7), w(4, p99=8), w(5, p99=9)])
        assert seq == [(HOLD, "breach_pending"), (HOLD, "in_band"),
                       (HOLD, "breach_pending"), (HOLD, "in_band"),
                       (HOLD, "in_band"), (HOLD, "in_band")]
        assert p._breach_streak == 0 and p._idle_streak == 0

    def test_idle_debounce_then_down(self):
        p = AutoscalePolicy(cfg())
        seq = run_seq(p, [w(t, p99=2) for t in range(4)])
        assert seq == [(HOLD, "idle_pending"), (HOLD, "idle_pending"),
                       (DOWN, "idle"), (HOLD, "cooldown")]

    def test_cooldown_suppresses_and_resets_streaks(self):
        """Breaching windows inside the cooldown are held AND do not
        accumulate: the first post-cooldown breach starts a fresh
        debounce."""
        p = AutoscalePolicy(cfg())
        seq = run_seq(p, [
            w(0, p99=20), w(1, p99=20),            # -> up at t=1
            w(2, p99=20), w(3, p99=20), w(5, p99=20),  # inside cooldown
            w(7, p99=20),                           # fresh streak: 1
            w(8, p99=20),                           # streak 2 -> up
        ])
        assert seq == [
            (HOLD, "breach_pending"), (UP, "slo:p99_ms"),
            (HOLD, "cooldown"), (HOLD, "cooldown"), (HOLD, "cooldown"),
            (HOLD, "breach_pending"), (UP, "slo:p99_ms"),
        ]

    def test_flap_budget_caps_oscillation(self):
        """Alternating breach/idle regimes force direction reversals;
        once the budget (2 reversals in the window) is spent, further
        reversals are suppressed with reason=flap."""
        p = AutoscalePolicy(cfg(breach_windows=1, idle_windows=1,
                                cooldown_s=0.0, flap_budget=2))
        seq = run_seq(p, [
            w(0, p99=20),   # up        (no reversal yet)
            w(1, p99=2),    # down      (reversal 1)
            w(2, p99=20),   # up        (reversal 2)
            w(3, p99=2),    # would reverse again -> flap
            w(4, p99=2),    # still flap
            w(5, p99=20),   # same direction as last executed (up): ok
        ])
        assert seq == [(UP, "slo:p99_ms"), (DOWN, "idle"),
                       (UP, "slo:p99_ms"), (HOLD, "flap"), (HOLD, "flap"),
                       (UP, "slo:p99_ms")]

    def test_operator_override_wins(self):
        """A /scale note suppresses automatic verbs for override_hold_s
        even under a hard breach; the loop resumes after the hold."""
        p = AutoscalePolicy(cfg())
        p.note_override(0.0)
        seq = run_seq(p, [w(1, p99=50), w(5, p99=50), w(9, p99=50),
                          w(11, p99=50), w(12, p99=50)])
        assert seq == [(HOLD, "override"), (HOLD, "override"),
                       (HOLD, "override"), (HOLD, "breach_pending"),
                       (UP, "slo:p99_ms")]

    def test_membership_bounds(self):
        p = AutoscalePolicy(cfg(breach_windows=1, idle_windows=1,
                                cooldown_s=0.0))
        assert p.decide(w(0, p99=50), gang_size=4).reason == "at_max"
        assert p.decide(w(1, p99=1), gang_size=1).reason == "at_min"

    def test_multi_signal_breach_names_every_signal(self):
        p = AutoscalePolicy(cfg(slo=SLOConfig(p99_ms=10, busy_ratio=0.2,
                                              staleness=4.0),
                                breach_windows=1))
        d = p.decide(w(0, p99=20, busy=0.5, stale=1.0), 2)
        assert d.action == UP
        assert d.reason == "slo:p99_ms+busy_ratio"
        assert d.breaches == ("p99_ms", "busy_ratio")

    def test_disabled_and_no_data(self):
        p = AutoscalePolicy(cfg(enabled=False))
        assert p.decide(w(0, p99=999), 2).reason == "disabled"
        p2 = AutoscalePolicy(cfg())
        assert p2.decide(None, 2).reason == "no_data"

    def test_replay_is_exact(self):
        """The whole contract in one line: two fresh policies fed the
        same window stream produce identical decision sequences."""
        windows = [w(t, p99=(30 if (t // 7) % 2 else 2),
                     gang=2 + (t % 2)) for t in range(40)]
        a = run_seq(AutoscalePolicy(cfg()), windows)
        b = run_seq(AutoscalePolicy(cfg()), windows)
        assert a == b

    def test_breach_episode_tracking(self):
        """breach_since anchors at the first breaching window and
        clears on recovery — the settle-window flight-dump trigger."""
        p = AutoscalePolicy(cfg())
        p.decide(w(3, p99=50), 4)
        assert p.breach_since == 3
        p.decide(w(4, p99=50), 4)
        assert p.breach_since == 3
        p.decide(w(5, p99=1), 4)
        assert p.breach_since is None


# ---------------------------------------------------------------------------
# the traffic generator: bit-reproducible schedules


class TestTrafficDeterminism:
    def test_same_seed_identical_schedule(self):
        for name in ("soak", "smoke", "bench"):
            a = Scenario.builtin(name, seed=7)
            b = Scenario.builtin(name, seed=7)
            assert a.schedule() == b.schedule()
            assert a.events_json() == b.events_json()

    def test_different_seed_different_schedule(self):
        a = Scenario.builtin("soak", seed=7)
        b = Scenario.builtin("soak", seed=8)
        assert a.schedule() != b.schedule()

    def test_schedule_is_stable_across_calls(self):
        s = Scenario.builtin("soak")
        assert s.schedule() == s.schedule()

    def test_grammar_round_trip(self):
        s = Scenario.parse(
            "seed=3,writers=1,readers=2,jitter=0;"
            "name=a,ticks=4,grads=2,reads=1.5,duty=0.7;"
            "name=b,ticks=6,reads=3,curve=sine,preempt_at=1+3,"
            "join_at=2,straggle_at=4,straggle_ticks=2,straggle_mult=3,"
            "duty=0.2")
        assert s.seed == 3 and s.writers == 1 and s.readers == 2
        assert s.shape_changes == 1 and s.total_ticks == 10
        kinds = {e.kind for e in s.schedule()}
        assert {GRAD, READ, PREEMPT, JOIN, STRAGGLE_ON,
                STRAGGLE_OFF} <= kinds
        # two preempt waves, round-robin targets
        waves = [e for e in s.schedule() if e.kind == PREEMPT]
        assert [e.target for e in waves] == [0, 1]
        # straggle_mult rides the event count
        on = next(e for e in s.schedule() if e.kind == STRAGGLE_ON)
        assert on.count == 3

    def test_grammar_rejects_unknowns_and_bad_bounds(self):
        with pytest.raises(ValueError, match="unknown phase field"):
            Scenario.parse("name=a,ticks=2,bogus=1")
        with pytest.raises(ValueError, match="duty"):
            Scenario.parse("name=a,ticks=2,duty=1.5")
        with pytest.raises(ValueError, match="curve"):
            Scenario.parse("name=a,ticks=2,curve=square")
        with pytest.raises(ValueError, match="outside"):
            Scenario.parse("name=a,ticks=2,preempt_at=5")
        with pytest.raises(ValueError, match="unknown scenario global"):
            Scenario.parse("seed=1,bogus=2;name=a,ticks=2")

    def test_fractional_reads_accumulate_exactly(self):
        """reads=0.5 with jitter off must dispatch exactly
        floor-accumulated read counts: 1 read every 2 ticks/reader."""
        s = Scenario.parse("seed=0,writers=1,readers=1,jitter=0;"
                           "name=a,ticks=8,grads=0,reads=0.5")
        reads = [e for e in s.schedule() if e.kind == READ]
        assert sum(e.count for e in reads) == 4

    def test_curves_shape_the_load(self):
        sine = TrafficPhase(name="s", ticks=8, reads=10, curve="sine")
        loads = [sine.load_at(i) for i in range(8)]
        assert max(loads) == max(loads[3], loads[4])  # rush mid-phase
        assert loads[0] < loads[3] and loads[7] < loads[4]
        ramp = TrafficPhase(name="r", ticks=4, reads=8, curve="ramp")
        assert [ramp.load_at(i) for i in range(4)] == [2.0, 4.0, 6.0, 8.0]

    def test_soak_scenario_meets_the_issue_bar(self):
        s = Scenario.builtin("soak")
        assert s.shape_changes >= 5
        kinds = {e.kind for e in s.schedule()}
        assert {GRAD, READ, PREEMPT, JOIN, STRAGGLE_ON} <= kinds

    def test_iter_ticks_covers_every_event_once(self):
        s = Scenario.builtin("smoke")
        flat = [e for _t, _p, evs in iter_ticks(s) for e in evs]
        assert flat == s.schedule()


# ---------------------------------------------------------------------------
# samplers: exposition -> windowed signals (exact delta arithmetic)


class TestSampling:
    def _registry_with(self, ops_ms, busy=0, grads=0, served=0,
                       stale=()):
        from mpit_tpu.obs.metrics import Registry

        reg = Registry()
        hist = reg.histogram("mpit_ps_op_seconds", op="GRAD", side="client")
        for ms in ops_ms:
            hist.observe(ms / 1000.0)
        if busy:
            reg.counter("mpit_ps_busy_replies_total", rank=0).inc(busy)
        if grads:
            reg.counter("mpit_ps_grads_applied_total", rank=0).inc(grads)
        if served:
            reg.counter("mpit_ps_params_served_total", rank=0).inc(served)
        for v in stale:
            reg.histogram("mpit_ps_grad_staleness", rank=0,
                          client=1).observe(v)
        return reg

    def test_hist_quantile_between_sees_only_the_window(self):
        reg = self._registry_with([1.0] * 2000)
        prev = obs_top.parse_exposition(reg.exposition())
        # the window adds 10 slow ops: the windowed p99 must jump to
        # the slow bucket even though the cumulative p99 stays low
        hist = reg.histogram("mpit_ps_op_seconds", op="GRAD", side="client")
        for _ in range(10):
            hist.observe(0.5)
        cur = obs_top.parse_exposition(reg.exposition())
        cum = obs_top.hist_quantile(cur, "mpit_ps_op_seconds", 0.99)
        win = obs_top.hist_quantile_between(prev, cur,
                                            "mpit_ps_op_seconds", 0.99)
        assert win >= 0.5 and cum < 0.5
        # empty window -> None
        assert obs_top.hist_quantile_between(cur, cur,
                                             "mpit_ps_op_seconds",
                                             0.99) is None

    def test_window_from_samples_delta_arithmetic(self):
        reg = self._registry_with([1.0] * 10, busy=2, grads=6, served=2,
                                  stale=[2.0, 4.0])
        prev = obs_top.parse_exposition(reg.exposition())
        reg.counter("mpit_ps_busy_replies_total", rank=0).inc(3)
        reg.counter("mpit_ps_grads_applied_total", rank=0).inc(9)
        reg.histogram("mpit_ps_grad_staleness", rank=0, client=1).observe(6.0)
        cur = obs_top.parse_exposition(reg.exposition())
        win = window_from_samples(5.0, cur, prev, gang_size=3)
        assert win.t == 5.0 and win.gang_size == 3
        assert win.ops == 9.0                       # applied delta only
        assert win.busy_ratio == pytest.approx(3 / (3 + 9))
        assert win.staleness == pytest.approx(6.0)  # the window's one obs
        # cold start (no prev): cumulative totals stand in
        cold = window_from_samples(1.0, cur, None)
        assert cold.ops == 17.0
        assert cold.busy_ratio == pytest.approx(5 / (5 + 17))

    def test_registry_sampler_reads_the_global_registry(self, obs_on):
        reg = obs.get_registry()
        reg.histogram("mpit_ps_op_seconds", op="GRAD",
                      side="client").observe(0.002)
        reg.counter("mpit_ps_grads_applied_total", rank=0).inc(4)
        sampler = RegistrySampler()
        first = sampler(1.0, gang_size=2)
        assert first.ops == 4.0 and first.p99_ms is not None
        reg.counter("mpit_ps_grads_applied_total", rank=0).inc(2)
        second = sampler(2.0, gang_size=2)
        assert second.ops == 2.0  # delta, not cumulative

    def test_http_sampler_pools_statusd_endpoints(self, obs_on):
        from mpit_tpu.obs import statusd

        reg = obs.get_registry()
        reg.histogram("mpit_ps_op_seconds", op="PARAM",
                      side="client").observe(0.004)
        reg.counter("mpit_ps_params_served_total", rank=0).inc(7)
        srv = statusd.StatusServer(0)
        try:
            sampler = HttpSampler(srv.port, nranks=1)
            win = sampler(1.0, gang_size=2)
            assert win.ops == 7.0
            assert win.p99_ms == pytest.approx(7.8125)  # log2 bucket
        finally:
            srv.close()

    def test_http_sampler_tolerates_down_ranks(self):
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        win = HttpSampler(dead_port, nranks=2)(3.0, gang_size=1)
        assert win.ops == 0.0 and win.p99_ms is None


# ---------------------------------------------------------------------------
# mpit top: SLO columns + the autoscale status line


class TestTopSlo:
    def _sample(self, busy=4, grads=12, autoscale=None):
        from mpit_tpu.obs.metrics import Registry

        reg = Registry()
        reg.histogram("mpit_ps_op_seconds", op="GRAD",
                      side="client").observe(0.020)
        reg.counter("mpit_ps_busy_replies_total", rank=0).inc(busy)
        reg.counter("mpit_ps_grads_applied_total", rank=0).inc(grads)
        status = {"role": "server"}
        if autoscale is not None:
            status["controller"] = {"autoscale": autoscale}
        return {"metrics": obs_top.parse_exposition(reg.exposition()),
                "status": status, "port": 1}

    def test_rank_row_busy_ratio_and_slo_verdict(self):
        row = obs_top._rank_row(0, self._sample(), None, None,
                                p99_target_ms=10.0)
        assert row["busy_ratio"] == pytest.approx(4 / 16)
        assert row["slo"] == "hot"  # 20ms observed vs 10ms target
        ok = obs_top._rank_row(0, self._sample(), None, None,
                               p99_target_ms=100.0)
        assert ok["slo"] == "ok"
        none = obs_top._rank_row(0, self._sample(), None, None)
        assert none["slo"] is None and none["p99_target_ms"] is None

    def test_render_table_has_slo_columns(self):
        row = obs_top._rank_row(0, self._sample(), None, None,
                                p99_target_ms=10.0)
        table = obs_top.render_table([row, {"rank": 1, "up": False}])
        head, body = table.splitlines()[0], table.splitlines()[1]
        assert "slo" in head and "busy%" in head
        assert "HOT" in body and "25" in body

    def test_autoscale_status_line(self):
        section = {
            "enabled": True, "slo": {"p99_ms": 24.0},
            "last": {"action": "up", "reason": "slo:p99_ms", "t": 1.0,
                     "breaches": ["p99_ms"], "cooldown_s": 0,
                     "window": None},
            "cooldown_s": 3.2,
            "decisions": {"up": 2, "down": 1, "hold": 40},
            "suppressed": 5, "operator_calls": 0,
        }
        samples = {0: self._sample(), 1: self._sample(autoscale=section)}
        found = obs_top.autoscale_status(samples)
        assert found == section
        line = obs_top.render_autoscale_line(found)
        assert "last=up(slo:p99_ms)" in line
        assert "cooldown=3.2s" in line and "up/down/hold=2/1/40" in line
        assert "p99_ms<=24" in line
        assert obs_top.render_autoscale_line(None) == \
            "autoscale: (not running)"


# ---------------------------------------------------------------------------
# flight dumps: autoscale postmortems validate (and bad ones don't)


class TestAutoscaleFlight:
    def _dump(self, tmp_path, monkeypatch, reason, **extra):
        from mpit_tpu.obs.flight import FlightRecorder, validate_dump

        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        rec = FlightRecorder()
        rec.set_identity(rank=0, role="controller")
        rec.record("autoscale", action="up", reason="slo:p99_ms",
                   executed=True)
        path = rec.dump(reason, **extra)
        assert path is not None
        return validate_dump, path

    def test_valid_autoscale_dump(self, tmp_path, monkeypatch, obs_on):
        decision = {"action": "up", "reason": "slo:p99_ms", "t": 1.0}
        window = {"p99_ms": 31.25, "ops": 40}
        validate, path = self._dump(tmp_path, monkeypatch, "autoscale_up",
                                    decision=decision, window=window)
        stats = validate(path)
        assert stats["reason"] == "autoscale_up" and stats["events"] >= 1

    def test_slo_breach_dump_needs_duration(self, tmp_path, monkeypatch,
                                            obs_on):
        decision = {"action": "hold", "reason": "at_max", "t": 9.0}
        validate, path = self._dump(tmp_path, monkeypatch, "slo_breach",
                                    decision=decision, window=None,
                                    breach_for_s=4.2)
        assert validate(path)["reason"] == "slo_breach"
        validate2, bad = self._dump(tmp_path, monkeypatch, "slo_breach",
                                    decision=decision, window=None)
        with pytest.raises(ValueError, match="breach_for_s"):
            validate2(bad)

    def test_dump_without_decision_rejected(self, tmp_path, monkeypatch,
                                            obs_on):
        validate, path = self._dump(tmp_path, monkeypatch, "autoscale_up",
                                    window=None)
        with pytest.raises(ValueError, match="decision"):
            validate(path)
        validate2, path2 = self._dump(tmp_path, monkeypatch,
                                      "autoscale_down",
                                      decision={"action": "down",
                                                "reason": "idle"})
        with pytest.raises(ValueError, match="window"):
            validate2(path2)


# ---------------------------------------------------------------------------
# the closed loop: scripted windows drive a REAL gang through real verbs


class TestClosedLoop:
    def _gang(self, tmp_path):
        """2 servers + 2 clients + controller + 1 spare on the local
        router — the same elastic topology the soak uses, grads
        serialized by the test."""
        from mpit_tpu.comm.local import LocalRouter
        from mpit_tpu.ft import FTConfig
        from mpit_tpu.ps import ParamClient, ParamServer
        from mpit_tpu.shardctl import ShardController

        ft = FTConfig(op_deadline_s=2.0, max_retries=10,
                      backoff_base_s=0.005, backoff_cap_s=0.02)
        router = LocalRouter(6)
        sranks, cranks, spare, ctl_rank = [0, 1], [2, 3], 4, 5
        servers, threads = {}, {}

        def make_server(r, joiner):
            servers[r] = ParamServer(
                r, list(cranks), router.endpoint(r), rule="add", ft=ft,
                controller_rank=ctl_rank, ckpt_dir=str(tmp_path),
                ckpt_interval=1e9, shardctl=joiner)
            threads[r] = threading.Thread(target=servers[r].start,
                                          daemon=True)
            threads[r].start()

        for r in sranks:
            make_server(r, joiner=False)
        ctl = ShardController(
            ctl_rank, router.endpoint(ctl_rank), sranks, cranks,
            spawner=lambda r: make_server(r, True), spare_ranks=[spare])
        clients = [ParamClient(r, sranks, router.endpoint(r),
                               seed_servers=(r == cranks[0]), ft=ft,
                               shardctl=True, controller_rank=ctl_rank,
                               sc_shards_per_server=2)
                   for r in cranks]
        w0 = np.arange(64, dtype=np.float32)
        starters = []
        for i, c in enumerate(clients):
            p = w0.copy() if i == 0 else np.zeros_like(w0)
            starters.append(threading.Thread(
                target=c.start, args=(p, np.zeros_like(w0)), daemon=True))
            starters[-1].start()
        for t in starters:
            t.join(30)
            assert not t.is_alive()
        ctl.pump()
        assert ctl.smap is not None
        return dict(ctl=ctl, clients=clients, servers=servers,
                    threads=threads)

    def _finish(self, gang):
        for c in gang["clients"]:
            c.stop()
        for t in gang["threads"].values():
            t.join(30)
            assert not t.is_alive()
        gang["ctl"].pump()
        assert gang["ctl"].done

    def test_breach_scales_up_and_idle_scales_down(self, tmp_path,
                                                   obs_on):
        """Scripted windows, real verbs: two breaching windows widen
        the gang onto the spawned spare; a later idle run drains it
        again.  The audit names the driving signal both times and the
        flight dumps validate."""
        from mpit_tpu.obs.flight import validate_dump

        gang = self._gang(tmp_path)
        ctl = gang["ctl"]
        script = iter([
            w(1, p99=50), w(2, p99=50),     # breach x2 -> up
            w(9, p99=1), w(10, p99=1), w(11, p99=1),  # idle x3 -> down
            w(12, p99=8),
        ])
        now = [0.0]
        scaler = Autoscaler(
            ctl, cfg(cooldown_s=0.0, window_s=0.0, idle_windows=3),
            sampler=lambda t, gang_size=0: next(script),
            clock=lambda: now[0])
        ctl.attach_autoscaler(scaler)
        for t in (1, 2):
            now[0] = t
            ctl.pump()
        assert scaler.ups == 1 and len(ctl._live_servers()) == 3
        up_rec = [d for d in scaler.audit_log() if d["action"] == UP][-1]
        assert up_rec["executed"] and up_rec["reason"] == "slo:p99_ms"
        assert up_rec["window"]["p99_ms"] == 50
        for t in (9, 10, 11):
            now[0] = t
            ctl.pump()
        assert scaler.downs == 1 and len(ctl._live_servers()) == 2
        assert 4 in ctl.retired  # the spare drained back out
        # the gang still trains end-to-end after both verbs
        c = gang["clients"][0]
        c.grad[:] = 1.0
        c.async_send_grad()
        c.wait()
        self._finish(gang)
        flight = obs.get_flight()
        assert flight.last_dump_path is not None
        validate_dump(flight.last_dump_path)

    def test_operator_route_suppresses_the_loop(self, tmp_path, obs_on,
                                                monkeypatch):
        """A queued /scale request (the HTTP handler's enqueue path)
        makes the very next breaching windows hold with
        reason=override — the human always wins."""
        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        gang = self._gang(tmp_path)
        ctl = gang["ctl"]
        now = [0.0]
        scaler = Autoscaler(
            ctl, cfg(cooldown_s=0.0, window_s=0.0, override_hold_s=100.0),
            sampler=lambda t, gang_size=0: w(t, p99=50),
            clock=lambda: now[0])
        ctl.attach_autoscaler(scaler)
        ctl._scale_action({"op": "down", "rank": "1"})
        assert scaler.operator_calls == 1
        for t in (1, 2, 3):
            now[0] = t
            ctl.pump()
        assert scaler.ups == 0
        reasons = [d["reason"] for d in scaler.audit_log()]
        assert reasons and set(reasons) == {"override"}
        # the operator's own request executed (rank 1 drained)
        assert 1 in ctl.retired
        assert ctl.autoscaler.status_section()["operator_calls"] == 1
        self._finish(gang)

    def test_failed_scale_up_is_audited_not_fatal(self, tmp_path, obs_on):
        """With no spare rank left the verb fails; the autoscaler logs
        the error in the audit record and the control plane keeps
        serving (never raises out of pump)."""
        gang = self._gang(tmp_path)
        ctl = gang["ctl"]
        ctl.spares.clear()
        now = [0.0]
        scaler = Autoscaler(
            ctl, cfg(cooldown_s=0.0, window_s=0.0),
            sampler=lambda t, gang_size=0: w(t, p99=50),
            clock=lambda: now[0])
        ctl.attach_autoscaler(scaler)
        for t in (1, 2):
            now[0] = t
            ctl.pump()
        assert scaler.ups == 0
        rec = scaler.audit_log()[-1]
        assert rec["action"] == UP and not rec["executed"]
        assert "spare" in rec["error"]
        self._finish(gang)


# ---------------------------------------------------------------------------
# status plumbing: the controller /status autoscale section


class TestStatusSection:
    def test_status_section_shape(self, obs_on):
        class _Ctl:
            rank = 9
            sranks = [0, 1]
            spares = []
            _clock = staticmethod(lambda: 0.0)

            def _live_servers(self):
                return [0, 1]

        scaler = Autoscaler(_Ctl(), cfg(), sampler=lambda t, gang_size=0:
                            w(t, p99=1), clock=lambda: 0.0)
        scaler.pump()
        section = scaler.status_section()
        assert section["slo"] == {"p99_ms": 10.0}
        assert section["last"]["action"] == HOLD
        assert section["decisions"]["hold"] == 1
        assert json.dumps(section)  # JSON-serializable for /status
