"""lm_launch CLI: the sequence-parallel LM trainer on the 8-virtual-device
mesh — learning, mesh-factorization equivalence, checkpoint/resume."""

import numpy as np
import pytest

from mpit_tpu.train.lm_launch import LM_LAUNCH_DEFAULTS, run

TINY = dict(seq_len=256, d_model=32, n_heads=4, n_layers=1, batch=8,
            attn_dtype="float32", log_every=10,
            # contiguous: zigzag (the production default) doubles the
            # flash-partial call count for the same math — it exists to
            # balance real multi-chip rings, and on the single-core CPU
            # test mesh it only doubles compile+run time.  The
            # factorization test pins a zigzag config explicitly.
            layout="contiguous")


def _cfg(**kw):
    base = dict(TINY)
    base.update(kw)
    return LM_LAUNCH_DEFAULTS.merged(base)


def test_learns_on_synthetic_bytes():
    res = run(_cfg(steps=40, lr=3e-3, dp=2, sp=4))
    losses = [h["avg_loss"] for h in res["history"]]
    assert all(np.isfinite(x) for x in losses)
    assert losses[-1] < losses[0] - 0.05, losses
    assert res["mesh"] == {"dp": 2, "sp": 4}


@pytest.mark.slow
def test_mesh_factorizations_agree():
    """Same seed, same global batches: dp x sp = 8 must produce the same
    training trajectory however the mesh is factored — the ring is exact
    attention and the loss is a global-batch mean."""
    results = {
        (dp, sp, layout): run(_cfg(steps=10, lr=1e-3, dp=dp, sp=sp,
                                   layout=layout, log_every=1))
        for dp, sp, layout in [
            (8, 1, "contiguous"), (2, 4, "contiguous"), (1, 8, "contiguous"),
            (2, 4, "zigzag"),  # balanced layout is exact attention too
        ]
    }
    base = [h["avg_loss"] for h in results[(8, 1, "contiguous")]["history"]]
    for key, res in results.items():
        losses = [h["avg_loss"] for h in res["history"]]
        np.testing.assert_allclose(losses, base, rtol=2e-4, atol=2e-5,
                                   err_msg=str(key))


@pytest.mark.slow
def test_checkpoint_resume_continues_stream(tmp_path):
    straight = run(_cfg(steps=20, lr=1e-3, dp=2, sp=4, log_every=5))
    run(_cfg(steps=10, lr=1e-3, dp=2, sp=4, log_every=5,
             ckpt_dir=str(tmp_path), ckpt_every=10))
    resumed = run(_cfg(steps=20, lr=1e-3, dp=2, sp=4, log_every=5,
                       ckpt_dir=str(tmp_path), resume="auto"))
    assert resumed["history"][0]["step"] >= 10
    np.testing.assert_allclose(
        resumed["history"][-1]["avg_loss"],
        straight["history"][-1]["avg_loss"], rtol=1e-5)


def test_init_with_dp_not_dividing_local_rows():
    """dp=4 sp=2 batch=8: batch//dp = 2 rows is NOT divisible by dp.
    The init sample's row count must be dp-divisible (it is shard_mapped
    over dp like a training batch); a (batch//dp)-row sample would crash
    at flatten_module for this valid config."""
    res = run(_cfg(steps=4, lr=1e-3, dp=4, sp=2, log_every=2))
    assert np.isfinite(res["history"][-1]["avg_loss"])


def test_resume_batch_mismatch_raises(tmp_path):
    run(_cfg(steps=2, lr=1e-3, dp=2, sp=4, log_every=2,
             ckpt_dir=str(tmp_path), ckpt_every=2))
    with pytest.raises(ValueError, match="batch"):
        run(_cfg(steps=8, lr=1e-3, dp=2, sp=4, batch=16, log_every=2,
                 ckpt_dir=str(tmp_path), resume="auto"))


def test_bad_factorization_raises():
    with pytest.raises(ValueError, match="devices"):
        run(_cfg(steps=1, dp=3, sp=2))
    with pytest.raises(ValueError, match="divisible"):
        run(_cfg(steps=1, dp=8, sp=1, batch=9))
