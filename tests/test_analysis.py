"""mtlint analyzer tests: seeded-violation fixtures must be detected by
the right rule at the right location, the clean fixture must be silent,
and — the tier-1 gate — the real tree must carry zero unsuppressed
findings under the checked-in mtlint.toml baseline.
"""

import pathlib
import subprocess
import sys

import pytest

from mpit_tpu.analysis import load_config, run
from mpit_tpu.analysis.config import ConfigError, parse_toml_subset

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "mtlint"
BADPKG = FIXTURES / "badpkg"
CLEANPKG = FIXTURES / "cleanpkg"


def _findings(target, config=None):
    return run(target, config).findings


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# -- seeded violations (the four the acceptance criteria name, plus the
# rest of the rule catalog) -------------------------------------------------


class TestSeededViolations:
    @pytest.fixture(scope="class")
    def bad(self):
        return _by_rule(_findings(BADPKG))

    def test_tag_mismatch_detected(self, bad):
        # Seed 1: client sends PING, server never receives it.
        hits = [f for f in bad.get("MT-P102", []) if "PING" in f.message]
        assert len(hits) == 1
        assert hits[0].path == "client.py"
        assert hits[0].line == 9

    def test_missing_ack_write_path_detected(self, bad):
        # Seed 2: push_grad ships GRAD without awaiting GRAD_ACK.
        hits = bad.get("MT-P103", [])
        assert len(hits) == 1
        assert (hits[0].path, hits[0].line) == ("client.py", 15)
        assert "GRAD" in hits[0].message and "GRAD_ACK" in hits[0].message

    def test_lock_order_inversion_detected(self, bad):
        # Seed 3: a_then_b takes _lock->_cv, b_then_a takes _cv->_lock.
        hits = bad.get("MT-C201", [])
        assert {(f.path, f.line) for f in hits} == {
            ("locks.py", 17), ("locks.py", 22)}

    def test_host_sync_in_jit_detected(self, bad):
        # Seed 4: float() on a traced value inside the jitted bad_step.
        hits = [f for f in bad.get("MT-J301", []) if "float()" in f.message]
        assert len(hits) == 1
        assert (hits[0].path, hits[0].line) == ("hotpath.py", 9)

    def test_unused_tag_detected(self, bad):
        hits = bad.get("MT-P101", [])
        assert [(f.path, f.line) for f in hits] == [("tags.py", 8)]
        assert "ORPHAN" in hits[0].message

    def test_recv_recv_deadlock_detected(self, bad):
        locs = {(f.path, f.line) for f in bad.get("MT-P104", [])}
        assert ("client.py", 21) in locs  # fetch: recv REPLY before send REQ

    def test_blocking_under_lock_detected(self, bad):
        locs = {(f.path, f.line) for f in bad.get("MT-C202", [])}
        assert ("locks.py", 27) in locs

    def test_unbounded_aio_detected(self, bad):
        # MT-P201: every badpkg aio call lacks deadline=/abort=.
        locs = {(f.path, f.line) for f in bad.get("MT-P201", [])}
        assert ("client.py", 9) in locs
        assert ("server.py", 16) in locs

    def test_blocking_convenience_detected(self, bad):
        # MT-P202: the seeded transport.recv() busy-wait in drain().
        hits = bad.get("MT-P202", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 22)]

    def test_event_loop_blocking_detected(self, bad):
        # MT-P203: raw recv + time.sleep + sendall inside _el_* callbacks
        # (tcp.py fixture); the cleanpkg _nb_*-helper shape must be silent
        # (asserted by test_clean_fixture_is_silent).
        hits = bad.get("MT-P203", [])
        assert {(f.path, f.line) for f in hits} == {
            ("tcp.py", 9), ("tcp.py", 11), ("tcp.py", 16)}
        assert all("event-loop callback" in f.message for f in hits)

    def test_signal_handler_blocking_detected(self, bad):
        # MT-P204: every call in the seeded SIGTERM handler (lock,
        # allocation, transport send, sleep) is a finding; the cleanpkg
        # flags-and-pipe handler must stay silent (asserted by
        # test_clean_fixture_is_silent).
        hits = bad.get("MT-P204", [])
        assert {(f.path, f.line) for f in hits} == {
            ("preempt.py", 18), ("preempt.py", 19),
            ("preempt.py", 20), ("preempt.py", 21)}
        assert all("SIGTERM handler" in f.message for f in hits)

    def test_yield_under_lock_detected(self, bad):
        hits = bad.get("MT-C203", [])
        assert [(f.path, f.line) for f in hits] == [("locks.py", 31)]

    def test_traced_branch_detected(self, bad):
        hits = bad.get("MT-J302", [])
        assert [(f.path, f.line) for f in hits] == [("hotpath.py", 10)]

    def test_missing_donate_detected(self, bad):
        locs = {(f.path, f.line) for f in bad.get("MT-J303", [])}
        assert ("hotpath.py", 19) in locs

    def test_raw_timing_detected(self, bad):
        # MT-O401: the seeded wall-clock read and the monotonic elapsed
        # subtraction in timing_report — deadline arithmetic elsewhere in
        # the fixtures (additions/comparisons) must not fire.
        locs = {(f.path, f.line) for f in bad.get("MT-O401", [])}
        assert locs == {("server.py", 28), ("server.py", 31)}

    def test_print_reporting_detected(self, bad):
        hits = bad.get("MT-O402", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 32)]
        assert "registry snapshot" in hits[0].message

    def test_unregistered_tag_detected(self, bad):
        # MT-P501: ROGUE is used by both roles (so MT-P101/P102 stay
        # quiet) but has no TAG_PAIRS entry.
        hits = bad.get("MT-P501", [])
        assert [(f.path, f.line) for f in hits] == [("tags.py", 9)]
        assert "ROGUE" in hits[0].message and "TAG_PAIRS" in hits[0].message

    def test_undocumented_tag_detected(self, bad):
        # MT-P502: ROGUE is absent from the fixture's docs/PROTOCOL.md.
        hits = bad.get("MT-P502", [])
        assert [(f.path, f.line) for f in hits] == [("tags.py", 9)]
        assert "PROTOCOL.md" in hits[0].message

    def test_undocumented_metric_detected(self, bad):
        # MT-O403: mpit_rogue_widgets_total is instantiated but absent
        # from the fixture's docs/OBSERVABILITY.md; the documented
        # mpit_good_widgets_total on the line above stays silent.
        hits = bad.get("MT-O403", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 46)]
        assert "mpit_rogue_widgets_total" in hits[0].message
        assert "OBSERVABILITY.md" in hits[0].message

    def test_undocumented_phase_detected(self, bad):
        # MT-O404: rogue_phase is marked but absent from the fixture's
        # docs/OBSERVABILITY.md phase taxonomy; the documented
        # good_phase on the line above stays silent.
        hits = bad.get("MT-O404", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 54)]
        assert "rogue_phase" in hits[0].message
        assert "OBSERVABILITY.md" in hits[0].message

    def test_dplane_host_transfer_detected(self, bad):
        # Seeds: np.asarray in apply_update, .item() + device_get in
        # sync_round — and nothing from the name-exempted
        # snapshot_host/timing_probe bodies.
        hits = bad.get("MT-J311", [])
        assert {(f.path, f.line) for f in hits} == {
            ("dplane/exchange.py", 10),
            ("dplane/exchange.py", 21),
            ("dplane/exchange.py", 22)}

    def test_dplane_device_barrier_detected(self, bad):
        hits = bad.get("MT-J312", [])
        assert [(f.path, f.line) for f in hits] == [
            ("dplane/exchange.py", 16)]
        assert "block_until_ready" in hits[0].message

    def test_nonbinary_pairs_exempt_from_role_model(self, bad):
        # The pairing table is what exempts controller / server<->server
        # tags from MT-P101/P102 — the badpkg table is all-binary, so
        # its seeded P101/P102 findings must be unaffected (asserted
        # elsewhere); here: the real tree's shardctl tags lean on it.
        from mpit_tpu.analysis.protocol import _binary_pair

        assert _binary_pair(None) is True
        assert _binary_pair(("client", "server")) is True
        assert _binary_pair(("server", "client")) is True
        assert _binary_pair(("server", "server")) is False
        assert _binary_pair(("controller|server", "server|client")) is False


def test_clean_fixture_is_silent():
    assert _findings(CLEANPKG) == []


# -- baseline / config ------------------------------------------------------


def test_repo_baseline_loads_and_every_entry_is_justified():
    cfg = load_config(REPO / "mtlint.toml")
    assert cfg.suppressions, "baseline exists but parsed empty"
    for s in cfg.suppressions:
        assert s.reason.strip(), f"unjustified baseline entry: {s.rule} @ {s.file}"


def test_baseline_rejects_entries_without_reason(tmp_path):
    bad = tmp_path / "mtlint.toml"
    bad.write_text('[[suppress]]\nrule = "MT-C202"\nfile = "x.py"\n')
    with pytest.raises(ConfigError, match="reason"):
        load_config(bad)


def test_toml_subset_parser_roundtrip():
    data = parse_toml_subset(
        '# comment\n[[suppress]]\nrule = "MT-X" # trailing\nline = 3\n'
        '[[suppress]]\nrule = "MT-Y"\nflags = ["a", "b"]\nok = true\n')
    assert data["suppress"][0] == {"rule": "MT-X", "line": 3}
    assert data["suppress"][1] == {"rule": "MT-Y", "flags": ["a", "b"],
                                   "ok": True}


def test_suppression_matching_and_unused_accounting():
    cfg = load_config(REPO / "mtlint.toml")
    report = run(REPO / "mpit_tpu", cfg)
    # Every baseline entry must still match a live finding — a stale
    # entry means the finding was fixed and the entry must be removed.
    assert report.unused_suppressions == [], [
        s.render() for s in report.unused_suppressions]


# -- the tier-1 gate --------------------------------------------------------


def test_real_tree_has_zero_unsuppressed_findings():
    cfg = load_config(REPO / "mtlint.toml")
    report = run(REPO / "mpit_tpu", cfg)
    assert report.findings == [], "\n" + "\n".join(
        f.render() for f in report.findings)


def test_cli_exit_codes():
    env_root = str(REPO)
    ok = subprocess.run(
        [sys.executable, "tools/mtlint.py", "mpit_tpu", "--quiet"],
        cwd=env_root, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "tools/mtlint.py",
         "tests/fixtures/mtlint/badpkg", "--quiet"],
        cwd=env_root, capture_output=True, text=True)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "MT-P103" in bad.stdout  # findings reach the console
