"""mtlint analyzer tests: seeded-violation fixtures must be detected by
the right rule at the right location, the clean fixture must be silent,
and — the tier-1 gate — the real tree must carry zero unsuppressed
findings under the checked-in mtlint.toml baseline.
"""

import pathlib
import subprocess
import sys

import pytest

from mpit_tpu.analysis import load_config, run
from mpit_tpu.analysis.config import ConfigError, parse_toml_subset

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "mtlint"
BADPKG = FIXTURES / "badpkg"
CLEANPKG = FIXTURES / "cleanpkg"


def _findings(target, config=None):
    return run(target, config).findings


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# -- seeded violations (the four the acceptance criteria name, plus the
# rest of the rule catalog) -------------------------------------------------


class TestSeededViolations:
    @pytest.fixture(scope="class")
    def bad(self):
        return _by_rule(_findings(BADPKG))

    def test_tag_mismatch_detected(self, bad):
        # Seed 1: client sends PING, server never receives it.
        hits = [f for f in bad.get("MT-P102", []) if "PING" in f.message]
        assert len(hits) == 1
        assert hits[0].path == "client.py"
        assert hits[0].line == 9

    def test_missing_ack_write_path_detected(self, bad):
        # Seed 2: push_grad ships GRAD without awaiting GRAD_ACK; seed 2b:
        # _post_push is a helper whose naked PARAM_PUSH send no caller
        # vouches for (the interprocedural scan must not excuse it).
        hits = sorted(bad.get("MT-P103", []), key=lambda f: f.line)
        assert len(hits) == 2
        assert (hits[0].path, hits[0].line) == ("client.py", 15)
        assert "GRAD" in hits[0].message and "GRAD_ACK" in hits[0].message
        assert (hits[1].path, hits[1].line) == ("client.py", 37)
        assert "PARAM_PUSH" in hits[1].message

    def test_helper_split_acks_are_followed(self, bad):
        # The §12/§13 helper-split shapes (cleanpkg stream_grads /
        # serve_grad_chunks / badpkg absorb_push) must be SILENT: the
        # scan follows one level of helper calls in both directions,
        # resolving parameter-carried tags at the call site.
        assert not [f for f in bad.get("MT-P103", [])
                    if "absorb_push" in f.message
                    or "_ack_push" in f.message]

    def test_lock_order_inversion_detected(self, bad):
        # Seed 3: a_then_b takes _lock->_cv, b_then_a takes _cv->_lock.
        hits = bad.get("MT-C201", [])
        assert {(f.path, f.line) for f in hits} == {
            ("locks.py", 17), ("locks.py", 22)}

    def test_host_sync_in_jit_detected(self, bad):
        # Seed 4: float() on a traced value inside the jitted bad_step.
        hits = [f for f in bad.get("MT-J301", []) if "float()" in f.message]
        assert len(hits) == 1
        assert (hits[0].path, hits[0].line) == ("hotpath.py", 9)

    def test_unused_tag_detected(self, bad):
        hits = bad.get("MT-P101", [])
        assert [(f.path, f.line) for f in hits] == [("tags.py", 8)]
        assert "ORPHAN" in hits[0].message

    def test_recv_recv_deadlock_detected(self, bad):
        locs = {(f.path, f.line) for f in bad.get("MT-P104", [])}
        assert ("client.py", 21) in locs  # fetch: recv REPLY before send REQ

    def test_blocking_under_lock_detected(self, bad):
        locs = {(f.path, f.line) for f in bad.get("MT-C202", [])}
        assert ("locks.py", 27) in locs

    def test_unbounded_aio_detected(self, bad):
        # MT-P201: every badpkg aio call lacks deadline=/abort=.
        locs = {(f.path, f.line) for f in bad.get("MT-P201", [])}
        assert ("client.py", 9) in locs
        assert ("server.py", 16) in locs

    def test_blocking_convenience_detected(self, bad):
        # MT-P202: the seeded transport.recv() busy-wait in drain().
        hits = bad.get("MT-P202", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 22)]

    def test_event_loop_blocking_detected(self, bad):
        # MT-P203: raw recv + time.sleep + sendall inside _el_* callbacks
        # (tcp.py fixture) PLUS the interprocedural seed: a raw recv one
        # helper below _el_on_timer, flagged at the blocking site inside
        # the helper.  The cleanpkg _nb_*-helper shapes (one and two
        # levels deep) must be silent (test_clean_fixture_is_silent).
        hits = bad.get("MT-P203", [])
        assert {(f.path, f.line) for f in hits} == {
            ("tcp.py", 9), ("tcp.py", 11), ("tcp.py", 16), ("tcp.py", 21)}
        assert all("event-loop callback" in f.message for f in hits)

    def test_event_loop_blocking_through_helper_names_the_path(self, bad):
        # The interprocedural finding must name both the helper that
        # blocks and the callback that reaches it — exactly once.
        hits = [f for f in bad.get("MT-P203", []) if f.line == 21]
        assert len(hits) == 1
        assert "_pump_once" in hits[0].message
        assert "_el_on_timer" in hits[0].message

    def test_interprocedural_blocking_under_lock_detected(self, bad):
        # MT-C202 via the call graph: hold_and_flush blocks one helper
        # down (slow_flush -> time.sleep) — exactly one finding, at the
        # call site under the lock.
        hits = [f for f in bad.get("MT-C202", [])
                if (f.path, f.line) == ("locks.py", 47)]
        assert len(hits) == 1
        assert "slow_flush" in hits[0].message

    def test_lock_across_scheduler_yield_detected(self, bad):
        # MT-Y803: hold_and_greet holds _lock across nap_via_sched(),
        # which re-enters the scheduler — exactly one finding.
        hits = bad.get("MT-Y803", [])
        assert [(f.path, f.line) for f in hits] == [("locks.py", 40)]
        assert "nap_via_sched" in hits[0].message

    def test_atomic_section_yield_detected(self, bad):
        # MT-Y801: a yield inside the declared read-gate window of the
        # fixture ps/server.py — exactly one finding.
        hits = bad.get("MT-Y801", [])
        assert [(f.path, f.line) for f in hits] == [("ps/server.py", 21)]
        assert "ps-read-gate-window" in hits[0].message

    def test_single_writer_escape_detected(self, bad):
        # MT-Y802: steal_ticket pops the device plane outside the
        # declared writer set — exactly one finding.  The cleanpkg twin
        # pops one helper BELOW the declared writer and must stay
        # silent (test_clean_fixture_is_silent).
        hits = bad.get("MT-Y802", [])
        assert [(f.path, f.line) for f in hits] == [("ps/server.py", 26)]
        assert "dplane-single-writer" in hits[0].message

    def test_unowned_buffer_at_seam_detected(self, bad):
        # MT-D901: a frombuffer view reaches the donated chunk apply,
        # plus the three pool-seam seeds (server scatter, client decode,
        # cells XOR out) — one finding each, nothing else.
        hits = bad.get("MT-D901", [])
        assert {(f.path, f.line) for f in hits} == {
            ("ps/server.py", 31), ("ps/server.py", 47),
            ("ps/client.py", 12), ("cells/wire.py", 12)}
        assert all("frombuffer" in f.message for f in hits)

    def test_ownership_wrapper_dropped_detected(self, bad):
        # MT-D903, both shapes: an unprovable sink argument
        # (ps/server.py) and a declared owned path whose device_copy
        # wrapper is gone (dplane/hbm.py) — plus the pool-seam
        # owned-copy paths: a stray np.array outside the submit
        # boundary on both the client decode and server scatter sides.
        hits = bad.get("MT-D903", [])
        assert {(f.path, f.line) for f in hits} == {
            ("ps/server.py", 36), ("dplane/hbm.py", 14),
            ("ps/client.py", 16), ("ps/server.py", 51)}

    def test_donated_slot_leak_detected(self, bad):
        # MT-D902: snapshot_host caches the bare donated buffer —
        # exactly one finding.
        hits = bad.get("MT-D902", [])
        assert [(f.path, f.line) for f in hits] == [("dplane/hbm.py", 19)]
        assert "self.param" in hits[0].message

    def test_signal_handler_blocking_detected(self, bad):
        # MT-P204: every call in the seeded SIGTERM handler (lock,
        # allocation, transport send, sleep) is a finding; the cleanpkg
        # flags-and-pipe handler must stay silent (asserted by
        # test_clean_fixture_is_silent).
        hits = bad.get("MT-P204", [])
        assert {(f.path, f.line) for f in hits} == {
            ("preempt.py", 18), ("preempt.py", 19),
            ("preempt.py", 20), ("preempt.py", 21)}
        assert all("SIGTERM handler" in f.message for f in hits)

    def test_yield_under_lock_detected(self, bad):
        hits = bad.get("MT-C203", [])
        assert [(f.path, f.line) for f in hits] == [("locks.py", 31)]

    def test_pool_wait_under_lock_detected(self, bad):
        # MT-C204 lock half: hold_and_collect blocks on a pool job with
        # _lock held (direct), hold_and_drain one helper down — one
        # finding each, at the call site under the lock.
        hits = sorted((f for f in bad.get("MT-C204", [])
                       if f.path == "pool.py"), key=lambda f: f.line)
        assert [(f.path, f.line) for f in hits] == [
            ("pool.py", 14), ("pool.py", 21)]
        assert "result" in hits[0].message
        assert "_drain_job" in hits[1].message

    def test_pool_wait_in_atomic_window_detected(self, bad):
        # MT-C204 window half: a Job.result() inside the declared
        # yield-free read-path window — exactly one finding, naming
        # the section.  The cleanpkg done()-under-lock and
        # join-outside-mutex twins must be silent
        # (test_clean_fixture_is_silent).
        hits = [f for f in bad.get("MT-C204", [])
                if f.path == "ps/server.py"]
        assert [(f.path, f.line) for f in hits] == [("ps/server.py", 41)]
        assert "ps-read-path-helpers" in hits[0].message

    def test_traced_branch_detected(self, bad):
        hits = bad.get("MT-J302", [])
        assert [(f.path, f.line) for f in hits] == [("hotpath.py", 10)]

    def test_missing_donate_detected(self, bad):
        locs = {(f.path, f.line) for f in bad.get("MT-J303", [])}
        assert ("hotpath.py", 19) in locs

    def test_raw_timing_detected(self, bad):
        # MT-O401: the seeded wall-clock read and the monotonic elapsed
        # subtraction in timing_report — deadline arithmetic elsewhere in
        # the fixtures (additions/comparisons) must not fire.
        locs = {(f.path, f.line) for f in bad.get("MT-O401", [])}
        assert locs == {("server.py", 28), ("server.py", 31)}

    def test_print_reporting_detected(self, bad):
        hits = bad.get("MT-O402", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 32)]
        assert "registry snapshot" in hits[0].message

    def test_unregistered_tag_detected(self, bad):
        # MT-P501: ROGUE is used by both roles (so MT-P101/P102 stay
        # quiet) but has no TAG_PAIRS entry.
        hits = bad.get("MT-P501", [])
        assert [(f.path, f.line) for f in hits] == [("tags.py", 9)]
        assert "ROGUE" in hits[0].message and "TAG_PAIRS" in hits[0].message

    def test_undocumented_tag_detected(self, bad):
        # MT-P502: ROGUE is absent from the fixture's docs/PROTOCOL.md.
        hits = bad.get("MT-P502", [])
        assert [(f.path, f.line) for f in hits] == [("tags.py", 9)]
        assert "PROTOCOL.md" in hits[0].message

    def test_undocumented_metric_detected(self, bad):
        # MT-O403: mpit_rogue_widgets_total is instantiated but absent
        # from the fixture's docs/OBSERVABILITY.md; the documented
        # mpit_good_widgets_total on the line above stays silent.
        hits = bad.get("MT-O403", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 46)]
        assert "mpit_rogue_widgets_total" in hits[0].message
        assert "OBSERVABILITY.md" in hits[0].message

    def test_undocumented_phase_detected(self, bad):
        # MT-O404: rogue_phase is marked but absent from the fixture's
        # docs/OBSERVABILITY.md phase taxonomy; the documented
        # good_phase on the line above stays silent.
        hits = bad.get("MT-O404", [])
        assert [(f.path, f.line) for f in hits] == [("server.py", 54)]
        assert "rogue_phase" in hits[0].message
        assert "OBSERVABILITY.md" in hits[0].message

    def test_dplane_host_transfer_detected(self, bad):
        # Seeds: np.asarray in apply_update, .item() + device_get in
        # sync_round — and nothing from the name-exempted
        # snapshot_host/timing_probe bodies.
        hits = bad.get("MT-J311", [])
        assert {(f.path, f.line) for f in hits} == {
            ("dplane/exchange.py", 10),
            ("dplane/exchange.py", 21),
            ("dplane/exchange.py", 22)}

    def test_dplane_device_barrier_detected(self, bad):
        hits = bad.get("MT-J312", [])
        assert [(f.path, f.line) for f in hits] == [
            ("dplane/exchange.py", 16)]
        assert "block_until_ready" in hits[0].message

    def test_nonbinary_pairs_exempt_from_role_model(self, bad):
        # The pairing table is what exempts controller / server<->server
        # tags from MT-P101/P102 — the badpkg table is all-binary, so
        # its seeded P101/P102 findings must be unaffected (asserted
        # elsewhere); here: the real tree's shardctl tags lean on it.
        from mpit_tpu.analysis.protocol import _binary_pair

        assert _binary_pair(None) is True
        assert _binary_pair(("client", "server")) is True
        assert _binary_pair(("server", "client")) is True
        assert _binary_pair(("server", "server")) is False
        assert _binary_pair(("controller|server", "server|client")) is False


def test_clean_fixture_is_silent():
    assert _findings(CLEANPKG) == []


# -- baseline / config ------------------------------------------------------


def test_repo_baseline_loads_and_every_entry_is_justified():
    cfg = load_config(REPO / "mtlint.toml")
    assert cfg.suppressions, "baseline exists but parsed empty"
    for s in cfg.suppressions:
        assert s.reason.strip(), f"unjustified baseline entry: {s.rule} @ {s.file}"


def test_baseline_rejects_entries_without_reason(tmp_path):
    bad = tmp_path / "mtlint.toml"
    bad.write_text('[[suppress]]\nrule = "MT-C202"\nfile = "x.py"\n')
    with pytest.raises(ConfigError, match="reason"):
        load_config(bad)


def test_toml_subset_parser_roundtrip():
    data = parse_toml_subset(
        '# comment\n[[suppress]]\nrule = "MT-X" # trailing\nline = 3\n'
        '[[suppress]]\nrule = "MT-Y"\nflags = ["a", "b"]\nok = true\n')
    assert data["suppress"][0] == {"rule": "MT-X", "line": 3}
    assert data["suppress"][1] == {"rule": "MT-Y", "flags": ["a", "b"],
                                   "ok": True}


def test_suppression_matching_and_unused_accounting():
    cfg = load_config(REPO / "mtlint.toml")
    report = run(REPO / "mpit_tpu", cfg)
    # Every baseline entry must still match a live finding — a stale
    # entry means the finding was fixed and the entry must be removed.
    assert report.unused_suppressions == [], [
        s.render() for s in report.unused_suppressions]


# -- the tier-1 gate --------------------------------------------------------


def test_real_tree_has_zero_unsuppressed_findings():
    cfg = load_config(REPO / "mtlint.toml")
    report = run(REPO / "mpit_tpu", cfg)
    assert report.findings == [], "\n" + "\n".join(
        f.render() for f in report.findings)


def test_cli_exit_codes():
    env_root = str(REPO)
    ok = subprocess.run(
        [sys.executable, "tools/mtlint.py", "mpit_tpu", "--quiet"],
        cwd=env_root, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "tools/mtlint.py",
         "tests/fixtures/mtlint/badpkg", "--quiet"],
        cwd=env_root, capture_output=True, text=True)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "MT-P103" in bad.stdout  # findings reach the console


# -- wire-schema conformance (MT-S6xx) --------------------------------------


class TestSchemaConformance:
    DRIFTPKG = FIXTURES / "driftpkg"

    @pytest.fixture(scope="class")
    def drift(self):
        return _by_rule(_findings(self.DRIFTPKG))

    def test_live_tree_is_conformant(self):
        from mpit_tpu.analysis import schema
        from mpit_tpu.analysis.core import collect

        files, errs = collect(REPO / "mpit_tpu")
        assert errs == []
        assert schema.check(files) == []

    def test_constant_drift_detected(self, drift):
        hits = drift.get("MT-S601", [])
        locs = {(f.path, f.line) for f in hits}
        assert ("ft/wire.py", 7) in locs  # HDR_BYTES = 24 vs schema 16
        assert any("HDR_BYTES" in f.message and "16" in f.message
                   for f in hits)
        # FLAG_ROGUE: a constant the registry does not declare
        assert any("FLAG_ROGUE" in f.message for f in hits)

    def test_struct_width_drift_detected(self, drift):
        hits = drift.get("MT-S602", [])
        # init_v3 grew to six words; rogue_frame is registered nowhere
        assert any("init_v3" in f.message and "6-word" in f.message
                   for f in hits)
        assert any("rogue_frame" in f.message for f in hits)

    def test_tag_registry_drift_detected(self, drift):
        msgs = [f.message for f in drift.get("MT-S603", [])]
        assert any("REDUCE = 18" in m for m in msgs)
        assert any("SIDEBAND" in m for m in msgs)
        assert any("TAG_PAIRS['DIFF']" in m for m in msgs)

    def test_clean_fixture_has_no_schema_findings(self):
        by = _by_rule(_findings(CLEANPKG))
        assert not any(r.startswith("MT-S6") for r in by)

    def test_negotiation_lattice_extraction_matches_schema(self):
        # The live _negotiate enforces exactly the declared REFUSALS —
        # asserted through the engine: zero MT-S604/S605 on the tree
        # (covered by test_live_tree_is_conformant) AND a doctored
        # guard is caught.
        import textwrap

        from mpit_tpu.analysis import schema
        from mpit_tpu.analysis.core import collect

        src = (REPO / "mpit_tpu" / "ps" / "server.py").read_text()
        # Drop the READONLY-requires-FRAMED guard: conformance must
        # notice the declared rule is no longer enforced.
        doctored = src.replace(
            "if ro and not (flags & FLAG_FRAMED):", "if False:")
        assert doctored != src
        import pathlib
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            p = pathlib.Path(td) / "ps" / "server.py"
            p.parent.mkdir()
            p.write_text(doctored)
            files, _ = collect(pathlib.Path(td))
            findings = schema.check(files)
        assert any(f.rule == "MT-S605" and "READONLY" in f.message
                   and "FRAMED" in f.message for f in findings), [
            f.render() for f in findings]


class TestSchemaDocs:
    def test_emit_docs_check_clean_on_tree(self):
        r = subprocess.run(
            [sys.executable, "-m", "mpit_tpu.analysis", "schema",
             "--emit-docs", "--check", "--root", str(REPO)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_check_nonzero_on_drift_fixture(self):
        r = subprocess.run(
            [sys.executable, "-m", "mpit_tpu.analysis", "schema",
             "--check", "--root",
             str(FIXTURES / "driftpkg")],
            capture_output=True, text=True)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "MT-S601" in r.stdout and "MT-S603" in r.stdout
        assert "doc drift" in r.stdout

    def test_generated_markers_present_in_protocol_md(self):
        doc = (REPO / "docs" / "PROTOCOL.md").read_text()
        for name in ("tag-table", "init-table", "flag-table"):
            assert f"BEGIN GENERATED: mtlint-schema {name}" in doc
            assert f"END GENERATED: mtlint-schema {name}" in doc

    def test_doc_drift_detected_after_hand_edit(self, tmp_path):
        from mpit_tpu.analysis import schema

        root = tmp_path / "docs"
        root.mkdir()
        doc = root / "PROTOCOL.md"
        src = (REPO / "docs" / "PROTOCOL.md").read_text()
        doc.write_text(src.replace("| `GRAD` (2) |", "| `GRAD` (99) |"))
        drift = schema.emit_docs(doc, check=True)
        assert any("tag-table" in d for d in drift)
        # and the clean copy is quiet
        doc.write_text(src)
        assert schema.emit_docs(doc, check=True) == []

    def test_oracle_agrees_with_declared_lattice(self):
        from mpit_tpu.analysis import schema

        # requires edges refuse
        for bits, missing in ((["SUBSCRIBE"], "READONLY"),
                              (["READONLY"], "FRAMED")):
            out = schema.negotiate(3, schema.flag_bits(*bits),
                                   reader_rank=True, cell_rank=True)
            assert not out.accepted and missing in out.reason
        # negotiate-off is silent, not a refusal
        out = schema.negotiate(3, schema.flag_bits("STALENESS"))
        assert out.accepted and not out.staleness
        out = schema.negotiate(
            3, schema.flag_bits("FRAMED", "STALENESS", "TIMING"))
        assert out.accepted and out.staleness and out.timing


# -- bounded interleaving model checker (MT-M7xx) ---------------------------


class TestModelCheck:
    MACHINES = FIXTURES / "machines"

    def test_live_handshakes_explore_clean(self):
        from mpit_tpu.analysis import modelcheck

        results = modelcheck.check_all()
        assert {r.machine for r in results} == {
            "init-grad-stop", "param-read", "retire", "preempt",
            "subscribe"}
        for r in results:
            assert r.clean, [v.render() for v in r.violations]
            assert r.states_fault_free > 0
            assert not r.truncated

    @pytest.mark.parametrize("fixture,rule", [
        ("deadlock.py", "MT-M701"),
        ("unreachable_ack.py", "MT-M702"),
        ("unacked_terminal.py", "MT-M703"),
    ])
    def test_seeded_fixture_fires(self, fixture, rule):
        from mpit_tpu.analysis import modelcheck

        machines = modelcheck.load_machines_file(self.MACHINES / fixture)
        results = modelcheck.check_all(machines)
        rules = {v.rule for r in results for v in r.violations}
        assert rule in rules, (fixture, rules)

    def test_deadlock_trace_names_both_blocked_recvs(self):
        from mpit_tpu.analysis import modelcheck

        machines = modelcheck.load_machines_file(
            self.MACHINES / "deadlock.py")
        (res,) = modelcheck.check_all(machines)
        (v,) = [v for v in res.violations if v.rule == "MT-M701"]
        assert "blocked on recv(REPLY)" in v.detail
        assert "blocked on recv(REQ)" in v.detail

    def test_cli_exit_codes_and_report(self, tmp_path):
        report = tmp_path / "mc.json"
        ok = subprocess.run(
            [sys.executable, "-m", "mpit_tpu.analysis", "modelcheck",
             "--report", str(report)],
            cwd=str(REPO), capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        import json

        data = json.loads(report.read_text())
        assert data["schema"] == "mpit_modelcheck/1"
        assert data["clean"] is True
        assert len(data["machines"]) == 5
        assert data["total_states"] > 0
        bad = subprocess.run(
            [sys.executable, "-m", "mpit_tpu.analysis", "modelcheck",
             "--machines",
             str(self.MACHINES / "deadlock.py")],
            cwd=str(REPO), capture_output=True, text=True)
        assert bad.returncode == 1
        assert "MT-M701" in bad.stdout

    def test_dup_toggle_widens_the_state_space(self):
        from mpit_tpu.analysis import modelcheck

        m = {r.machine: r for r in modelcheck.check_all()}
        r = m["init-grad-stop"]
        assert r.states_faulty > r.states_fault_free


# -- declared concurrency/ownership disciplines (MT-Y8xx / MT-D9xx) ---------


class TestDisciplines:
    def test_real_tree_disciplines_all_verified(self):
        # The acceptance gate: every declared discipline matches live
        # code sites (no stale declarations) and verifies clean.
        from mpit_tpu.analysis import disciplines

        rep = disciplines.coverage_report(REPO / "mpit_tpu")
        assert rep["schema"] == "mpit_disciplines/1"
        assert rep["stale"] == 0, [
            r["name"] for r in rep["disciplines"] if r["status"] == "stale"]
        assert rep["violated"] == 0, [
            r for r in rep["disciplines"] if r["status"] == "violated"]
        assert rep["verified"] >= 6
        # The minimum coverage the spec names: the §11 read-gate window,
        # one single-writer per plane, and the donation seam.
        names = {r["name"] for r in rep["disciplines"]}
        assert {"ps-read-gate-window", "dplane-single-writer",
                "aggplane-single-writer", "reader-single-writer",
                "cell-stream-single-writer",
                "chunk-apply-owned-seam",
                "pool-client-decode-owned", "pool-server-scatter-owned",
                "cells-xor-owned-out"} <= names

    def test_cli_report_and_exit_codes(self, tmp_path):
        report = tmp_path / "disc.json"
        ok = subprocess.run(
            [sys.executable, "-m", "mpit_tpu.analysis", "disciplines",
             "--report", str(report)],
            cwd=str(REPO), capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        import json

        data = json.loads(report.read_text())
        assert data["schema"] == "mpit_disciplines/1"
        assert data["verified"] >= 6 and data["stale"] == 0
        assert all(r["status"] == "verified" for r in data["disciplines"])

    def test_stale_declaration_gate(self, tmp_path):
        # A tree with none of the declared files: every row is stale and
        # the CLI fails — a registry that matches nothing is drift, the
        # same spirit as a stale baseline entry.
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "other.py").write_text("def f():\n    return 1\n")
        r = subprocess.run(
            [sys.executable, "-m", "mpit_tpu.analysis", "disciplines",
             "--root", str(pkg)],
            cwd=str(REPO), capture_output=True, text=True)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "stale" in r.stdout

    # -- mutation proofs: breaking a guarded site turns the tree red --------

    def _doctored(self, tmp_path, rel, old, new):
        import pathlib as _p

        src = (REPO / "mpit_tpu" / rel).read_text()
        assert old in src
        doctored = src.replace(old, new)
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(doctored)
        from mpit_tpu.analysis.core import collect

        files, errs = collect(_p.Path(tmp_path))
        assert errs == []
        return files

    def test_yield_in_read_gate_window_turns_tree_red(self, tmp_path):
        from mpit_tpu.analysis import disciplines

        files = self._doctored(
            tmp_path, "ps/server.py",
            "gate = self._read_gate()",
            "gate = self._read_gate()\n        yield None")
        findings = disciplines.check(files)
        assert any(f.rule == "MT-Y801" for f in findings), [
            f.render() for f in findings]

    def test_bypassing_chunk_owned_turns_tree_red(self, tmp_path):
        from mpit_tpu.analysis import ownership

        files = self._doctored(
            tmp_path, "ps/server.py",
            "self._chunk_owned(body.view(self.dtype))",
            "body.view(self.dtype)")
        findings = ownership.check(files)
        assert any(f.rule in ("MT-D901", "MT-D903") for f in findings), [
            f.render() for f in findings]

    def test_dropping_device_copy_on_seed_turns_tree_red(self, tmp_path):
        from mpit_tpu.analysis import ownership

        files = self._doctored(
            tmp_path, "dplane/hbm.py",
            "self.param = device_copy(place_flat(value, self.config))",
            "self.param = place_flat(value, self.config)")
        findings = ownership.check(files)
        assert any(f.rule == "MT-D903" for f in findings), [
            f.render() for f in findings]

    def test_dropping_decode_snapshot_turns_tree_red(self, tmp_path):
        # The pool seam's ownership pin: submitting the reused rx frame
        # to a pooled decode without the np.array snapshot must flag.
        from mpit_tpu.analysis import ownership

        files = self._doctored(
            tmp_path, "ps/client.py",
            "self.codec, np.array(body), out[lo:hi])",
            "self.codec, body, out[lo:hi])")
        findings = ownership.check(files)
        assert any(f.rule in ("MT-D901", "MT-D903") for f in findings), [
            f.render() for f in findings]

    def test_pool_wait_in_real_window_turns_tree_red(self, tmp_path):
        # MT-C204's window half against the real tree: a blocking
        # Job.result() planted inside _snapshot_wire (a declared
        # yield-free read-path helper) must flag.
        from mpit_tpu.analysis import callgraph, concurrency

        files = self._doctored(
            tmp_path, "ps/server.py",
            'def _snapshot_wire(self, codec: "codec_mod.Codec") '
            "-> np.ndarray:",
            'def _snapshot_wire(self, codec: "codec_mod.Codec") '
            "-> np.ndarray:\n        self.job.result()")
        graph = callgraph.build_graph(files)
        findings = concurrency.check(files, graph)
        assert any(f.rule == "MT-C204" for f in findings), [
            f.render() for f in findings]

    def test_caching_bare_snapshot_turns_tree_red(self, tmp_path):
        from mpit_tpu.analysis import ownership

        files = self._doctored(
            tmp_path, "dplane/hbm.py",
            "self._snap_host = (self.version, np.asarray(self.param))",
            "self._snap_host = (self.version, self.param)")
        findings = ownership.check(files)
        assert any(f.rule == "MT-D902" for f in findings), [
            f.render() for f in findings]

    def test_spawn_inside_window_is_not_a_yield(self):
        # The semantic pin the whole family rests on: sched.spawn(gen())
        # primes only the NEW task (aio/scheduler.py), so the clean
        # fixture's _dispatch_read — which spawns a generator inside the
        # declared window — must verify (covered by
        # test_clean_fixture_is_silent; asserted here directly).
        from mpit_tpu.analysis import callgraph, disciplines
        from mpit_tpu.analysis.core import collect

        files, _ = collect(CLEANPKG)
        graph = callgraph.build_graph(files)
        section = next(s for s in disciplines.SECTIONS
                       if s.name == "ps-read-gate-window")
        assert disciplines.section_findings(graph, section) == []


# -- content-hash suppression keys ------------------------------------------


class TestContentHashBaseline:
    def test_repo_baseline_is_content_keyed(self):
        cfg = load_config(REPO / "mtlint.toml")
        assert all(s.content for s in cfg.suppressions), [
            s.render() for s in cfg.suppressions if not s.content]

    def test_content_key_survives_line_moves(self, tmp_path):
        from mpit_tpu.analysis.core import content_key

        body = (
            "import tags\n"
            "from aio import aio_send\n\n\n"
            "def push_grad(transport, grad):\n"
            "    yield from aio_send(transport, grad, 0, tags.GRAD)\n")
        tagmod = "GRAD = 1\nGRAD_ACK = 2\n" \
                 "TAG_PAIRS = {'GRAD': ('client', 'server'), " \
                 "'GRAD_ACK': ('server', 'client')}\n"
        srv = ("import tags\nfrom aio import aio_recv, aio_send\n\n\n"
               "def serve(transport, buf):\n"
               "    yield from aio_recv(transport, 1, tags.GRAD, out=buf)\n"
               "    yield from aio_send(transport, b'', 1, tags.GRAD_ACK)\n"
               "    yield from aio_recv(transport, 1, tags.GRAD_ACK)\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "tags.py").write_text(tagmod)
        (pkg / "server.py").write_text(srv)
        (pkg / "client.py").write_text(body)
        flagged = ("yield from aio_send(transport, grad, 0, tags.GRAD)")
        key = content_key(flagged)
        (tmp_path / "mtlint.toml").write_text(
            '[[suppress]]\nrule = "MT-P103"\nfile = "pkg/client.py"\n'
            f'content = "{key}"\nreason = "test: content key"\n'
            '[[suppress]]\nrule = "MT-P201"\nfile = "pkg/client.py"\n'
            'line = 6\nreason = "test: line key for the same site"\n'
            '[[suppress]]\nrule = "MT-P201"\nfile = "pkg/server.py"\n'
            'reason = "test: file-wide for the server recv/sends"\n')
        cfg = load_config(tmp_path / "mtlint.toml")
        r1 = run(pkg, cfg)
        assert not [f for f in r1.findings if f.rule == "MT-P103"], [
            f.render() for f in r1.findings]
        # Move the flagged line down 20 lines: the content entry still
        # matches; the line-pinned MT-P201 entry goes stale.
        (pkg / "client.py").write_text(
            "import tags\nfrom aio import aio_send\n" + "\n" * 20 + body)
        cfg = load_config(tmp_path / "mtlint.toml")
        r2 = run(pkg, cfg)
        assert not [f for f in r2.findings if f.rule == "MT-P103"]
        assert [f for f in r2.findings if f.rule == "MT-P201"]
        stale = [s for s in r2.unused_suppressions if s.line == 6]
        assert stale, "line-pinned entry should have gone stale"

    def test_malformed_content_key_rejected(self, tmp_path):
        bad = tmp_path / "mtlint.toml"
        bad.write_text('[[suppress]]\nrule = "MT-C202"\nfile = "x.py"\n'
                       'content = "nothex"\nreason = "r"\n')
        with pytest.raises(ConfigError, match="content"):
            load_config(bad)

    def test_suggest_baseline_prints_content_entries(self):
        r = subprocess.run(
            [sys.executable, "tools/mtlint.py",
             "tests/fixtures/mtlint/badpkg", "--suggest-baseline",
             "--no-config"],
            cwd=str(REPO), capture_output=True, text=True)
        assert r.returncode == 1
        assert "[[suppress]]" in r.stdout
        assert 'content = "' in r.stdout
        # The new families get content-keyed entries like everyone else.
        for rule in ("MT-Y801", "MT-Y802", "MT-Y803",
                     "MT-D901", "MT-D902", "MT-D903"):
            assert f'rule = "{rule}"' in r.stdout, rule

    def test_suggest_baseline_rejects_colliding_content_key(self, tmp_path):
        # An existing baseline entry already claims the content hash of
        # a flagged line (under a different rule, so the finding stays
        # unsuppressed).  Suggesting another content entry with the same
        # key would silently merge the two — the CLI must pin by line
        # instead, loudly.
        from mpit_tpu.analysis.core import content_key

        flagged = (BADPKG / "locks.py").read_text().splitlines()[26]
        key = content_key(flagged)  # locks.py:27 — the MT-C202 seed
        cfg = tmp_path / "mtlint.toml"
        cfg.write_text(
            '[[suppress]]\nrule = "MT-C203"\nfile = "locks.py"\n'
            f'content = "{key}"\n'
            'reason = "test: same content hash claimed by another rule"\n')
        r = subprocess.run(
            [sys.executable, "tools/mtlint.py",
             "tests/fixtures/mtlint/badpkg", "--suggest-baseline",
             "--config", str(cfg)],
            cwd=str(REPO), capture_output=True, text=True)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "already claimed" in r.stdout
        assert f'content = "{key}"' not in r.stdout
        assert "line = 27" in r.stdout
