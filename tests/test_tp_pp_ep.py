"""Tensor-, pipeline-, and expert-parallel primitives vs unsharded
oracles on the 8-virtual-device CPU mesh — sharded == dense to float
tolerance, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpit_tpu.parallel import (
    ep_moe,
    moe_reference,
    pipeline,
    stack_stage_params,
    tp_mlp,
    tp_self_attention,
)


def _mesh(axis, n=8):
    from mpit_tpu.utils.platform import default_devices

    return Mesh(np.array(default_devices()[:n]), (axis,))


def _arr(rng, *shape):
    return jnp.asarray(rng.normal(size=shape) * 0.3, jnp.float32)


class TestTensorParallel:
    def test_mlp_matches_dense(self, rng):
        mesh = _mesh("tp")
        d, h = 16, 64  # h divisible by 8
        x = _arr(rng, 4, 10, d)
        w1, b1 = _arr(rng, d, h), _arr(rng, h)
        w2, b2 = _arr(rng, h, d), _arr(rng, d)
        out = jax.jit(tp_mlp(mesh))(x, w1, b1, w2, b2)
        ref = jnp.einsum(
            "...h,hd->...d", jax.nn.gelu(jnp.einsum("...d,dh->...h", x, w1) + b1), w2
        ) + b2
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_mlp_grads(self, rng):
        mesh = _mesh("tp")
        d, h = 8, 32
        x = _arr(rng, 2, 6, d)
        w1, b1, w2, b2 = _arr(rng, d, h), _arr(rng, h), _arr(rng, h, d), _arr(rng, d)
        f = tp_mlp(mesh)

        def ref(x, w1, b1, w2, b2):
            hh = jax.nn.gelu(jnp.einsum("...d,dh->...h", x, w1) + b1)
            return jnp.einsum("...h,hd->...d", hh, w2) + b2

        g1 = jax.grad(lambda *a: jnp.sum(f(*a) ** 2), argnums=(1, 3))(x, w1, b1, w2, b2)
        g2 = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2), argnums=(1, 3))(x, w1, b1, w2, b2)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_attention_matches_dense(self, rng):
        from mpit_tpu.ops.flash_attention import attention_reference

        mesh = _mesh("tp")
        B, L, d, H = 2, 12, 16, 8
        dh = d // H
        x = _arr(rng, B, L, d)
        wqkv = _arr(rng, d, 3, H, dh)
        wo = _arr(rng, H, dh, d)
        out = jax.jit(tp_self_attention(mesh, causal=True))(x, wqkv, wo)

        qkv = jnp.einsum("bld,dthk->btlhk", x, wqkv)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        heads = attention_reference(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=True,
        ).transpose(0, 2, 1, 3)
        ref = jnp.einsum("blhk,hkd->bld", heads, wo)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestPipeline:
    def _stage(self, params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def test_matches_sequential(self, rng):
        mesh = _mesh("pp")
        n, d, m, B = 8, 12, 5, 4
        stages = [
            {"w": _arr(rng, d, d), "b": _arr(rng, d)} for _ in range(n)
        ]
        stacked = stack_stage_params(stages)
        xs = _arr(rng, m, B, d)
        out = jax.jit(pipeline(mesh, self._stage))(stacked, xs)

        ref = xs
        for p in stages:
            ref = jax.vmap(lambda mb, p=p: self._stage(p, mb))(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_backprop_through_pipe(self, rng):
        mesh = _mesh("pp")
        n, d, m, B = 8, 8, 4, 2
        stages = [{"w": _arr(rng, d, d), "b": _arr(rng, d)} for _ in range(n)]
        stacked = stack_stage_params(stages)
        xs = _arr(rng, m, B, d)
        pipe = pipeline(mesh, self._stage)

        def loss_pipe(stacked):
            return jnp.sum(pipe(stacked, xs) ** 2)

        def loss_ref(stacked):
            ref = xs
            for i in range(n):
                p = jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
                ref = jax.vmap(lambda mb, p=p: self._stage(p, mb))(ref)
            return jnp.sum(ref ** 2)

        g1 = jax.grad(loss_pipe)(stacked)
        g2 = jax.grad(loss_ref)(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


class TestMoE:
    def test_matches_reference(self, rng):
        mesh = _mesh("ep")
        E, d, h = 16, 8, 16
        x = _arr(rng, 3, 7, d)
        gate = _arr(rng, d, E)
        w1, b1 = _arr(rng, E, d, h), _arr(rng, E, h)
        w2, b2 = _arr(rng, E, h, d), _arr(rng, E, d)
        out = jax.jit(ep_moe(mesh))(x, gate, w1, b1, w2, b2)
        ref = moe_reference(x, gate, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_router_grads_flow(self, rng):
        mesh = _mesh("ep")
        E, d, h = 8, 8, 8
        x = _arr(rng, 2, 5, d)
        gate = _arr(rng, d, E)
        w1, b1 = _arr(rng, E, d, h), _arr(rng, E, h)
        w2, b2 = _arr(rng, E, h, d), _arr(rng, E, d)
        f = ep_moe(mesh)
        g_gate, g_w1 = jax.grad(
            lambda gate, w1: jnp.sum(f(x, gate, w1, b1, w2, b2) ** 2),
            argnums=(0, 1),
        )(gate, w1)
        gr_gate, gr_w1 = jax.grad(
            lambda gate, w1: jnp.sum(moe_reference(x, gate, w1, b1, w2, b2) ** 2),
            argnums=(0, 1),
        )(gate, w1)
        np.testing.assert_allclose(np.asarray(g_gate), np.asarray(gr_gate), atol=5e-5)
        np.testing.assert_allclose(np.asarray(g_w1), np.asarray(gr_w1), atol=5e-5)
        # The router actually receives gradient (combine weight path).
        assert float(jnp.max(jnp.abs(g_gate))) > 0
