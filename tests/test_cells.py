"""Multi-cell serving fabric (docs/PROTOCOL.md §11): SUBSCRIBE posture,
diff-stream replication (bitwise frame equality), staleness-bounded
admission under injected diff-stream faults, kill-a-cell reader
failover with zero RetryExhausted, consistent-hash routing, and the
per-cell autoscale binding."""

import threading
import time

import numpy as np
import pytest

from mpit_tpu.cells import wire as cellwire
from mpit_tpu.cells.cell import ServingCell
from mpit_tpu.cells.ring import CellRing
from mpit_tpu.comm.tcp import TcpTransport, allocate_local_addresses
from mpit_tpu.ft import (
    FLAG_FRAMED,
    FLAG_READONLY,
    FLAG_SUBSCRIBE,
    FaultPlan,
    FaultyTransport,
    FTConfig,
    RetryExhausted,
    init_v3,
)
from mpit_tpu.ps import ParamClient, ParamServer, ReaderClient, tags
from mpit_tpu.ps.serve import parse_serve_header, serve_head


# ---------------------------------------------------------------------------
# wire units


class TestDiffWire:
    def test_pack_parse_roundtrip(self):
        body = np.arange(64, dtype=np.uint8)
        msg = cellwire.pack_diff(cellwire.DIFF_DELTA, 3, 5, 7, body)
        kind, f, t, head, out = cellwire.parse_diff(msg)
        assert (kind, f, t, head) == (cellwire.DIFF_DELTA, 3, 5, 7)
        np.testing.assert_array_equal(out, body)
        # headless FULL-with-empty-body parses too
        msg = cellwire.pack_diff(cellwire.DIFF_FULL, -1, 0, 0,
                                 np.zeros(0, np.uint8))
        assert cellwire.parse_diff(msg)[4].size == 0

    def test_chunked_pack_parse_roundtrip(self):
        """§11.8: a frame's chunk-message sequence reassembles to the
        exact body; a small body ships as one chunk message."""
        body = np.arange(100, dtype=np.uint8)
        msgs = cellwire.pack_diff_chunks(cellwire.DIFF_DELTA, 3, 5, 7,
                                         body, chunk_bytes=40)
        assert len(msgs) == 3
        pieces = []
        for i, msg in enumerate(msgs):
            kind, f, t, head, idx, count, piece = \
                cellwire.parse_diff_chunk(msg)
            assert (kind, f, t, head) == (cellwire.DIFF_DELTA, 3, 5, 7)
            assert (idx, count) == (i, 3)
            pieces.append(piece)
        np.testing.assert_array_equal(np.concatenate(pieces), body)
        assert len(cellwire.pack_diff_chunks(
            cellwire.DIFF_FULL, -1, 1, 1, body, chunk_bytes=1024)) == 1

    def test_malformed_frames_are_loud(self):
        with pytest.raises(ValueError, match="too short"):
            cellwire.parse_diff(b"\x00" * 8)
        msg = cellwire.pack_diff(cellwire.DIFF_FULL, -1, 1, 1,
                                 np.zeros(16, np.uint8))
        with pytest.raises(ValueError, match="promised"):
            cellwire.parse_diff(bytes(msg)[:-4])
        bad = np.frombuffer(bytes(msg), np.uint8).copy()
        bad[:8].view(np.int64)[0] = 99  # unknown kind
        with pytest.raises(ValueError, match="kind"):
            cellwire.parse_diff(bad)

    def test_xor_delta_is_exact_involution(self):
        rng = np.random.default_rng(7)
        a = rng.standard_normal(257).astype(np.float32)
        b = rng.standard_normal(257).astype(np.float32)
        delta = cellwire.xor_delta(a, b)
        rebuilt = cellwire.apply_delta(a, delta)
        # Bitwise — not allclose: the fabric's replication guarantee.
        assert rebuilt.tobytes() == b.tobytes()
        with pytest.raises(ValueError, match="size"):
            cellwire.xor_delta(a, np.zeros(3, np.uint8))

    def test_frame_history_bounded_and_memoized(self):
        hist = cellwire.FrameHistory(keep=3)
        frames = {v: np.full(8, v, np.uint8) for v in range(6)}
        for v, f in frames.items():
            hist.record(v, f)
        assert not hist.has(0) and not hist.has(2) and hist.has(3)
        d1 = hist.delta(4, 5)
        d2 = hist.delta(4, 5)
        assert d1 is d2  # memoized for the N-cells-same-version case
        np.testing.assert_array_equal(
            d1, np.bitwise_xor(frames[4], frames[5]))
        with pytest.raises(ValueError):
            cellwire.FrameHistory(keep=1)


class TestRing:
    def test_deterministic_and_covers_members(self):
        ring = CellRing([4, 5, 6], vnodes=16)
        assignments = {r: ring.lookup(r) for r in range(40)}
        assert assignments == {r: CellRing([4, 5, 6], vnodes=16).lookup(r)
                               for r in range(40)}
        assert set(assignments.values()) == {4, 5, 6}

    def test_down_member_only_moves_its_own_readers(self):
        ring = CellRing([4, 5, 6], vnodes=32)
        before = {r: ring.lookup(r) for r in range(64)}
        victim = 5
        ring.mark_down(victim)
        after = {r: ring.lookup(r) for r in range(64)}
        for r in range(64):
            if before[r] != victim:
                assert after[r] == before[r], "stable arc moved"
            else:
                assert after[r] != victim
        ring.mark_up(victim)
        assert {r: ring.lookup(r) for r in range(64)} == before

    def test_successors_and_exhaustion(self):
        ring = CellRing([2, 3], vnodes=8)
        succ = ring.successors(11)
        assert sorted(succ) == [2, 3] and succ[0] == ring.lookup(11)
        ring.mark_down(2)
        ring.mark_down(3)
        with pytest.raises(LookupError):
            ring.lookup(11)
        with pytest.raises(ValueError):
            CellRing([])


# ---------------------------------------------------------------------------
# posture validation (no I/O)


class TestPosture:
    def test_server_validates_subscribe_posture(self):
        server = ParamServer(0, [1], transport=None, reader_ranks=[2],
                             cell_ranks=[3])
        base = FLAG_FRAMED | FLAG_READONLY
        # subscribe without READONLY
        with pytest.raises(ValueError, match="FLAG_READONLY"):
            server._negotiate(3, init_v3(
                0, 16, 0, 0, FLAG_FRAMED | FLAG_SUBSCRIBE).tobytes())
        # subscribe from a non-cell rank
        with pytest.raises(ValueError, match="cell_ranks"):
            server._negotiate(2, init_v3(
                0, 16, 0, 0, base | FLAG_SUBSCRIBE).tobytes())
        # a cell rank must announce the posture
        with pytest.raises(ValueError, match="FLAG_SUBSCRIBE"):
            server._negotiate(3, init_v3(0, 16, 0, 0, base).tobytes())
        # the real thing is accepted
        codec = server._negotiate(3, init_v3(
            0, 16, 0, 0, base | FLAG_SUBSCRIBE).tobytes())
        assert codec.name == "none" and server._subscribe[3]

    def test_cell_roles_disjoint_and_shardctl_exclusive(self):
        with pytest.raises(ValueError, match="overlap"):
            ParamServer(0, [1], transport=None, cell_ranks=[1])
        with pytest.raises(ValueError, match="overlap"):
            ParamServer(0, [1], transport=None, reader_ranks=[2],
                        cell_ranks=[2])
        from mpit_tpu.shardctl.shardmap import ShardMap
        from mpit_tpu.shardctl.wire import init_v4
        server = ParamServer(0, [1], transport=None, cell_ranks=[3])
        smap = ShardMap.initial(64, [0])
        with pytest.raises(ValueError, match="mutually exclusive"):
            server._negotiate(1, init_v4(0, 0, FLAG_FRAMED,
                                         smap).tobytes())

    def test_cell_validates_reader_attach(self):
        cell = ServingCell(5, 0, None, [7], size=64,
                           ft=FTConfig(heartbeat_s=0.1, op_deadline_s=5.0))
        good = FLAG_FRAMED | FLAG_READONLY
        with pytest.raises(ValueError, match="read-only"):
            cell._negotiate(7, init_v3(0, 64, 0, 0, 0).tobytes())
        with pytest.raises(ValueError, match="reader_ranks"):
            cell._negotiate(9, init_v3(0, 64, 0, 0, good).tobytes())
        with pytest.raises(ValueError, match="mirrors"):
            cell._negotiate(7, init_v3(0, 32, 0, 0, good).tobytes())
        with pytest.raises(ValueError, match="subscription codec"):
            cell._negotiate(7, init_v3(0, 64, 2, 0, good).tobytes())
        with pytest.raises(ValueError, match="not to cells"):
            cell._negotiate(7, init_v3(
                0, 64, 0, 0, good | FLAG_SUBSCRIBE).tobytes())
        assert cell._negotiate(7, init_v3(
            0, 64, 0, 0, good).tobytes()).name == "none"

    def test_cell_requires_heartbeats(self):
        with pytest.raises(ValueError, match="heartbeat"):
            ServingCell(5, 0, None, [7], size=64,
                        ft=FTConfig(op_deadline_s=5.0))

    def test_serve_header_head_word(self):
        cell = ServingCell(5, 0, None, [7], size=64,
                           ft=FTConfig(heartbeat_s=0.1, op_deadline_s=5.0))
        cell._install(np.zeros(8, np.uint8), 6)
        cell._note_head(9)
        hdr = cell._serve_ok_header(1, 2)
        assert parse_serve_header(hdr)[:2] == (1, 2)
        assert serve_head(hdr) == 9
        # direct-server 4-word replies have no head word
        from mpit_tpu.ps.serve import serve_reply
        assert serve_head(serve_reply(1, 2, 0, 6)) is None


class TestFlightShapes:
    def test_cell_dump_shapes_validated(self, tmp_path):
        import json

        from mpit_tpu.obs import flight as obs_flight

        base = {"schema": "mpit_flight/1", "reason": "cell_lag_shed",
                "pid": 1, "wall_time": 0.0, "events": [], "metrics": {}}
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(base))
        with pytest.raises(ValueError, match="extra"):
            obs_flight.validate_dump(str(bad))
        bad.write_text(json.dumps({**base, "extra": {"window": {}}}))
        with pytest.raises(ValueError, match="version"):
            obs_flight.validate_dump(str(bad))
        bad.write_text(json.dumps(
            {**base, "extra": {"window": {"version": 3}}}))
        with pytest.raises(ValueError, match="head"):
            obs_flight.validate_dump(str(bad))
        good = tmp_path / "good.json"
        good.write_text(json.dumps({**base, "extra": {
            "window": {"version": 3, "head": 9, "max_lag": 4}}}))
        assert obs_flight.validate_dump(str(good))["reason"] == \
            "cell_lag_shed"
        fo = {**base, "reason": "cell_failover",
              "extra": {"window": {"version": 3, "dead": 2,
                                   "successor": 4}}}
        good.write_text(json.dumps(fo))
        assert obs_flight.validate_dump(str(good))["reason"] == \
            "cell_failover"


# ---------------------------------------------------------------------------
# the fabric end-to-end (in-process TCP gangs)

SIZE = 2048


def _build_mesh(core, nranks, extra_addrs=0):
    addrs, socks = allocate_local_addresses(core)
    addrs = addrs + ["127.0.0.1:0"] * (nranks - core)
    tr = {}

    def build(r):
        tr[r] = TcpTransport(r, nranks, addrs, listener=socks[r],
                             reconnect=30.0, dial_peers=list(range(r)))

    ths = [threading.Thread(target=build, args=(r,)) for r in range(core)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    assert all(r in tr for r in range(core)), "core mesh construction hung"
    return addrs, tr


class _Gang:
    """1 server (rank 0) + 1 writer (rank 1) + N cells + M readers."""

    def __init__(self, ncells=2, nreaders=2, *, server_wrap=None,
                 max_lag=4, cell_hb=0.05, server_ft=None,
                 cell_chunk_bytes=0):
        self.ncells, self.nreaders = ncells, nreaders
        core = 2 + ncells
        self.nranks = core + nreaders
        self.cell_ranks = list(range(2, 2 + ncells))
        self.reader_ranks = list(range(core, self.nranks))
        self.addrs, self.tr = _build_mesh(core, self.nranks)
        ep = self.tr[0] if server_wrap is None else server_wrap(self.tr[0])
        self.server = ParamServer(
            0, [1], ep, rule="add", cell_ranks=self.cell_ranks,
            ft=server_ft or FTConfig(lease_ttl_s=10.0))
        self.sth = threading.Thread(target=self.server.start, daemon=True)
        self.sth.start()
        self.cells = {}
        self.cth = {}
        for c in self.cell_ranks:
            cell = ServingCell(
                c, 0, self.tr[c], reader_ranks=self.reader_ranks,
                size=SIZE, max_lag=max_lag,
                ft=FTConfig(heartbeat_s=cell_hb, op_deadline_s=10.0,
                            chunk_bytes=cell_chunk_bytes))
            self.cells[c] = cell

            def run(cell=cell):
                try:
                    cell.start()
                except RuntimeError:
                    pass  # killed mid-run (the chaos legs)

            self.cth[c] = threading.Thread(target=run, daemon=True)
            self.cth[c].start()
        self.client = ParamClient(1, [0], self.tr[1], seed_servers=True,
                                  ft=FTConfig(op_deadline_s=30.0))
        self.param = np.arange(SIZE, dtype=np.float32)
        self.grad = np.ones(SIZE, np.float32)
        self.client.start(self.param.copy(), self.grad)

    def commit(self, n=1):
        """n grad applies => n committed versions (each adds 1.0)."""
        for _ in range(n):
            self.client.async_send_grad()
            self.client.wait()

    def expected(self, version):
        """The upstream snapshot at ``version`` (seed = version 1)."""
        return self.param + float(max(version - 1, 0))

    def finish(self, timeout=60):
        self.client.stop()
        for c, t in self.cth.items():
            t.join(timeout)
            assert not t.is_alive(), f"cell {c} never stopped"
        self.sth.join(timeout)
        assert not self.sth.is_alive(), "server never stopped"

    def close(self):
        for r, t in self.tr.items():
            t.close()


def _reader(gang, rank, rounds, out, deadline_s=10.0, read_sleep=0.0,
            failover_after=2):
    t = TcpTransport(rank, gang.nranks, gang.addrs, reconnect=30.0,
                     dial_peers=gang.cell_ranks, listen=False)
    rc = ReaderClient(rank, [0], t,
                      cells={0: gang.cell_ranks},
                      failover_after=failover_after,
                      ft=FTConfig(op_deadline_s=deadline_s,
                                  max_retries=8))
    mirror = np.zeros(SIZE, np.float32)
    rc.start(mirror)
    reads = []
    errors = []
    try:
        for _ in range(rounds):
            rc.read_params()
            v = rc.read_versions[0]
            reads.append((v, dict(rc.lags), mirror.copy()))
            if read_sleep:
                time.sleep(read_sleep)
    except RetryExhausted as exc:
        errors.append(exc)
    out[rank] = {"reads": reads, "errors": errors,
                 "monotone": rc.monotone, "failovers": rc.failovers,
                 "busy_honored": rc.busy_honored}
    rc.stop()
    t.close()


class TestFabric:
    def test_cells_serve_bitwise_with_one_diff_stream(self):
        """2 cells x 2 readers: every read decodes bit-for-bit the
        upstream snapshot at its stamped version, versions are monotone
        per cell, reader lag never exceeds the bound, and the upstream
        answered no reader PARAM at all — the cells absorbed the read
        fan-out on one diff stream each."""
        gang = _Gang(ncells=2, nreaders=2)
        try:
            gang.commit(3)
            out = {}
            rth = [threading.Thread(target=_reader,
                                    args=(gang, r, 5, out))
                   for r in gang.reader_ranks]
            for t in rth:
                t.start()
            gang.commit(3)
            for t in rth:
                t.join(60)
                assert not t.is_alive(), "reader hung"
            gang.finish()
            served_by_cells = 0
            for r in gang.reader_ranks:
                rec = out[r]
                assert not rec["errors"]
                assert rec["monotone"]
                assert rec["failovers"] == 0
                for v, lags, mirror in rec["reads"]:
                    np.testing.assert_array_equal(mirror,
                                                  gang.expected(v))
                    assert lags[0] <= 4
            for cell in gang.cells.values():
                served_by_cells += cell.params_served
                assert cell.version == gang.server._snap_version
                assert cell.diffs_installed >= 1
            assert served_by_cells == 2 * 5  # every read hit a cell
            # the upstream's PARAM serves came from the writer only
            # (its read_params during start); readers never touched it.
            assert gang.server.params_served <= 2
        finally:
            gang.close()

    def test_chunk_framed_subscription_bitwise(self):
        """§11.8: a FLAG_CHUNKED subscription receives FULL/DELTA
        frames as chunk messages (SIZE=2048 f32 at a 4 KiB cut = 2
        chunks per frame) — reads stay bit-for-bit the upstream
        snapshot, and the server actually shipped chunk messages."""
        gang = _Gang(ncells=2, nreaders=2, cell_chunk_bytes=4096)
        try:
            gang.commit(3)
            out = {}
            rth = [threading.Thread(target=_reader,
                                    args=(gang, r, 4, out))
                   for r in gang.reader_ranks]
            for t in rth:
                t.start()
            gang.commit(3)
            for t in rth:
                t.join(60)
                assert not t.is_alive(), "reader hung"
            chunks_sent = int(gang.server._m_diff_chunks.value)
            gang.finish()
            for r in gang.reader_ranks:
                rec = out[r]
                assert not rec["errors"]
                assert rec["monotone"]
                for v, _lags, mirror in rec["reads"]:
                    np.testing.assert_array_equal(mirror,
                                                  gang.expected(v))
            assert chunks_sent >= 2, (
                "no chunk messages shipped — the subscription never "
                "negotiated FLAG_CHUNKED?")
            for cell in gang.cells.values():
                assert cell.version == gang.server._snap_version
        finally:
            gang.close()

    def test_chunk_framed_subscription_survives_chunk_drops(self):
        """Chunk-level drop/dup on the DIFF channel: a torn frame is
        exactly a dropped frame — the gap/resync machinery recovers
        and every installed version stays bit-exact."""
        def wrap(t):
            return FaultyTransport(t, FaultPlan(seed=3, drop_every=5,
                                               dup_every=4,
                                               tags=frozenset({tags.DIFF})))

        gang = _Gang(ncells=1, nreaders=1, cell_chunk_bytes=4096,
                     server_wrap=wrap)
        try:
            for _ in range(6):
                gang.commit(1)
                time.sleep(0.05)
            deadline = time.monotonic() + 20
            cell = gang.cells[2]
            while time.monotonic() < deadline and \
                    cell.version < gang.server._snap_version:
                time.sleep(0.05)
            assert cell.version >= 1, "cell never installed a frame"
            np.testing.assert_array_equal(
                np.frombuffer(bytes(cell._frame), np.float32),
                gang.expected(cell.version))
            cell.shutdown()  # no reader ever attaches in this leg
            gang.finish()
        finally:
            gang.close()

    def test_kill_a_cell_readers_reroute_zero_retry_exhausted(self):
        """SIGKILL-shaped cell death (transport torn, no STOP, no
        GOODBYE): every reader routed to the dead cell fails over to
        the live sibling inside its retry loop — zero RetryExhausted,
        reads stay bitwise-correct."""
        gang = _Gang(ncells=2, nreaders=4)
        try:
            gang.commit(2)
            out = {}
            rth = [threading.Thread(
                target=_reader,
                args=(gang, r, 8, out),
                kwargs=dict(deadline_s=0.5, read_sleep=0.05))
                for r in gang.reader_ranks]
            for t in rth:
                t.start()
            time.sleep(0.3)  # a few reads land pre-kill
            # Kill one cell abruptly: close its transport (every link
            # torn at once — exactly what a SIGKILL looks like to the
            # peers; the lease reaper owns the upstream side).
            victim = gang.cell_ranks[0]
            gang.tr[victim].close()
            gang.commit(2)
            for t in rth:
                t.join(90)
                assert not t.is_alive(), "reader hung after cell kill"
            # The gang still shuts down: the dead cell's lease expires
            # (ttl 10s) or the survivors' STOPs settle first.
            survivor = gang.cells[gang.cell_ranks[1]]
            failovers = 0
            for r in gang.reader_ranks:
                rec = out[r]
                assert not rec["errors"], rec["errors"]
                failovers += rec["failovers"]
                for v, _lags, mirror in rec["reads"]:
                    np.testing.assert_array_equal(mirror,
                                                  gang.expected(v))
            assert failovers >= 1, "nobody was routed to the victim?"
            assert survivor.params_served > 0
            gang.client.stop()
        finally:
            gang.close()

    def test_goodbye_retire_reroutes_readers(self):
        """Graceful cell retirement (the autoscale drain verb): readers
        follow GOODBYE-with-successor to the sibling without burning
        retry budget, and the retired cell stops cleanly."""
        gang = _Gang(ncells=2, nreaders=2)
        try:
            gang.commit(2)
            out = {}
            rth = [threading.Thread(
                target=_reader, args=(gang, r, 10, out),
                kwargs=dict(read_sleep=0.03))
                for r in gang.reader_ranks]
            for t in rth:
                t.start()
            time.sleep(0.15)
            victim, survivor = gang.cell_ranks
            gang.cells[victim].retire_serving(survivor)
            gang.commit(2)
            for t in rth:
                t.join(60)
                assert not t.is_alive(), "reader hung across retire"
            gang.finish()
            for r in gang.reader_ranks:
                rec = out[r]
                assert not rec["errors"]
                for v, _lags, mirror in rec["reads"]:
                    np.testing.assert_array_equal(mirror,
                                                  gang.expected(v))
        finally:
            gang.close()


class TestStalenessEnforcement:
    """The acceptance bar: the bound is enforced, not advisory."""

    def test_property_no_read_beyond_max_lag_under_faults(self):
        """Seeded drop/delay FaultPlans on the DIFF channel: across
        plans, every answered read is bitwise-equal to the upstream
        snapshot at its stamped version, and the stamped (version,
        head) window never exceeds max_lag — the gate arithmetic holds
        under exactly the faults it exists for.  Drops force resyncs
        (the FULL path); delays force the lag window open."""
        max_lag = 2
        plans = [
            FaultPlan(seed=1, drop_every=3, tags=frozenset({tags.DIFF})),
            FaultPlan(seed=2, delay_every=2, delay_polls=200,
                      tags=frozenset({tags.DIFF})),
            FaultPlan(seed=3, drop_rate=0.3, delay_rate=0.3,
                      delay_polls=120, tags=frozenset({tags.DIFF})),
        ]
        for plan in plans:
            gang = _Gang(
                ncells=1, nreaders=2, max_lag=max_lag, cell_hb=0.02,
                server_wrap=lambda tr, plan=plan: FaultyTransport(tr, plan))
            try:
                gang.commit(2)
                out = {}
                rth = [threading.Thread(
                    target=_reader, args=(gang, r, 6, out),
                    kwargs=dict(read_sleep=0.02))
                    for r in gang.reader_ranks]
                for t in rth:
                    t.start()
                gang.commit(8)
                for t in rth:
                    t.join(120)
                    assert not t.is_alive(), f"reader hung under {plan}"
                gang.finish(timeout=90)
                for r in gang.reader_ranks:
                    rec = out[r]
                    assert not rec["errors"], (plan, rec["errors"])
                    assert rec["monotone"]
                    for v, lags, mirror in rec["reads"]:
                        # bitwise vs the upstream snapshot at the
                        # stamped version
                        np.testing.assert_array_equal(
                            mirror, gang.expected(v))
                        # the enforced envelope: stamped head minus
                        # served version, never beyond the bound
                        assert lags[0] <= max_lag, (plan, v, lags)
            finally:
                gang.close()

    def test_lag_shed_busy_and_recovery(self):
        """Hold the diff stream shut while committing past max_lag:
        the cell (told the head by its beat echoes) sheds reads as
        BUSY; when the stream reopens it catches up and the parked
        reads complete — bitwise, within the bound."""
        max_lag = 2
        # every DIFF delayed a long-but-finite number of polls
        plan = FaultPlan(seed=9, delay_every=1, delay_polls=2500,
                         tags=frozenset({tags.DIFF}))
        gang = _Gang(ncells=1, nreaders=1, max_lag=max_lag, cell_hb=0.02,
                     server_wrap=lambda tr: FaultyTransport(tr, plan))
        try:
            gang.commit(1)
            cell = gang.cells[2]
            # let the first (delayed) FULL land so the cell serves
            deadline = time.monotonic() + 30
            while cell.version < 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert cell.version >= 0, "cell never installed a frame"
            # commit far past the bound; beats tell the cell the head
            gang.commit(max_lag + 4)
            deadline = time.monotonic() + 30
            while cell.lag <= max_lag and time.monotonic() < deadline:
                time.sleep(0.02)
            assert cell.lag > max_lag, "beat echoes never moved the head"
            out = {}
            th = threading.Thread(
                target=_reader, args=(gang, gang.reader_ranks[0], 3, out),
                kwargs=dict(deadline_s=20.0))
            th.start()
            th.join(120)
            assert not th.is_alive(), "reader hung in the shed window"
            gang.finish(timeout=90)
            rec = out[gang.reader_ranks[0]]
            assert not rec["errors"]
            assert rec["busy_honored"] >= 1, \
                "no BUSY crossed the shed window"
            assert cell.lag_sheds >= 1
            for v, lags, mirror in rec["reads"]:
                np.testing.assert_array_equal(mirror, gang.expected(v))
                assert lags[0] <= max_lag
        finally:
            gang.close()


@pytest.mark.slow
def test_launch_cells_mode_end_to_end():
    """`--cells N` through the real process-gang launcher: cells sit
    between the training roles and the readers, subscribe to their
    upstream servers, and the readers report monotone versions + bounded
    lag served entirely by the cells."""
    from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

    cfg = LAUNCH_DEFAULTS.merged(
        np=7, serve_readers=2, cells=2, opt="downpour", epochs=1,
        model="linear", side=8, batch=64, ft_op_deadline_s=60.0,
        ft_heartbeat_s=0.2, serve_rounds=4, serve_interval_s=0.02,
        ring_mb=8,
    )
    results = launch_processes(cfg, timeout=600)
    for r in (3, 4):
        assert results[r]["role"] == "cell"
        assert results[r]["diffs_installed"] >= 1
        assert results[r]["params_served"] >= 1 or True  # routing may skew
    served = sum(results[r]["params_served"] for r in (3, 4))
    assert served >= 8  # 2 readers x 4 rounds all landed on cells
    for r in (5, 6):
        assert results[r]["role"] == "reader"
        assert results[r]["monotone"] is True
        assert results[r]["reads"] == 4
        assert all(v <= cfg.cell_max_lag
                   for v in results[r]["lags"].values())
    assert results[1]["role"] == "worker"


# ---------------------------------------------------------------------------
# autoscale binding


class TestCellAutoscaler:
    def _scaler(self, samples_seq, cells, **cfg_kw):
        from mpit_tpu.cells.autoscale import CellAutoscaler, CellSLO
        from mpit_tpu.shardctl.autoscale import AutoscaleConfig

        cfg = AutoscaleConfig(
            slo=CellSLO(max_lag=4.0).to_slo(),
            window_s=1.0, breach_windows=2, idle_windows=4,
            cooldown_s=0.0, min_servers=1, max_servers=4, **cfg_kw)
        verbs = []
        scaler = CellAutoscaler(
            cfg,
            add_cell=lambda: verbs.append("up") or True,
            drain_cell=lambda: verbs.append("down") or True,
            live_cells=lambda: list(cells))
        t = [0.0]
        scaler._clock = lambda: t[0]
        seq = iter(samples_seq)
        scaler._sample = lambda: next(seq)
        return scaler, verbs, t

    @staticmethod
    def _sample(lag, rank=2):
        return [("mpit_cell_lag", {"rank": str(rank)}, float(lag)),
                ("mpit_ps_params_served_total", {"rank": str(rank)},
                 100.0)]

    def test_lag_breach_scales_up_idle_drains(self):
        cells = [2]
        hot = self._sample(9)
        cold = self._sample(0)
        scaler, verbs, t = self._scaler(
            [hot, hot, hot, cold, cold, cold, cold, cold], cells)
        actions = []
        for _ in range(8):
            t[0] += 1.5
            d = scaler.pump()
            actions.append(d.action)
            if d.action == "up":
                cells.append(3)
            if d.action == "down" and len(cells) > 1:
                cells.pop()
        assert "up" in actions, actions
        assert verbs[0] == "up"
        # after the breach cleared, sustained idle drains the spare
        assert "down" in actions, actions
        assert scaler.audit and all("window" in a for a in scaler.audit)

    def test_min_bound_holds_drain(self):
        cells = [2]
        cold = self._sample(0)
        scaler, verbs, t = self._scaler([cold] * 6, cells)
        for _ in range(6):
            t[0] += 1.5
            d = scaler.pump()
        assert verbs == []  # at min_servers: hold, never drain
        assert any(a["reason"] == "at_min" for a in scaler.audit)

    def test_cell_window_restricts_to_cell_ranks(self):
        from mpit_tpu.cells.autoscale import cell_window

        cur = [("mpit_cell_lag", {"rank": "2"}, 3.0),
               ("mpit_cell_lag", {"rank": "9"}, 50.0),  # not a cell
               ("mpit_ps_params_served_total", {"rank": "2"}, 10.0),
               ("mpit_ps_params_served_total", {"rank": "0"}, 999.0),
               ("mpit_ps_busy_replies_total", {"rank": "2"}, 10.0)]
        w = cell_window(1.0, cur, None, [2])
        assert w.staleness == 3.0
        assert w.ops == 10.0
        assert w.busy_ratio == 0.5
        assert w.gang_size == 1
