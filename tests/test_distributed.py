"""Multi-host bootstrap helpers: hostfile parsing, resolution order, and
a real single-process jax.distributed group formation."""

import pytest

from mpit_tpu.parallel import ProcessGroup, bootstrap, read_hostfile
from mpit_tpu.parallel.distributed import coordinator_from_hostfile


class TestHostfile:
    def test_reference_format(self, tmp_path):
        p = tmp_path / "hosts"
        p.write_text("bluejgpu1:16\nbluejgpu2:16\n\n# comment\nbluejgpu3:16\n")
        entries = read_hostfile(p)
        assert [e.host for e in entries] == ["bluejgpu1", "bluejgpu2", "bluejgpu3"]
        assert all(e.slots == 16 for e in entries)

    def test_default_slots_and_coordinator(self, tmp_path):
        p = tmp_path / "hosts"
        p.write_text("alpha\nbeta:4\n")
        entries = read_hostfile(p)
        assert entries[0].slots == 1 and entries[1].slots == 4
        coord, n = coordinator_from_hostfile(entries, port=9999)
        assert coord == "alpha:9999" and n == 2

    def test_empty_raises(self, tmp_path):
        p = tmp_path / "hosts"
        p.write_text("# nothing\n")
        with pytest.raises(ValueError):
            read_hostfile(p)

    def test_bad_line_raises(self, tmp_path):
        p = tmp_path / "hosts"
        p.write_text(":8\n")
        with pytest.raises(ValueError):
            read_hostfile(p)


class TestBootstrap:
    def test_single_host_noop(self, monkeypatch):
        for var in ("MPIT_COORDINATOR", "MPIT_NUM_PROCESSES",
                    "MPIT_PROCESS_ID", "MPIT_HOSTFILE"):
            monkeypatch.delenv(var, raising=False)
        pg = bootstrap()
        assert pg == ProcessGroup(0, 1, None)
        assert len(pg.devices) >= 1
        assert "single-host" in pg.describe()

    def test_rank_range_validated(self):
        with pytest.raises(ValueError):
            bootstrap(coordinator="localhost:1", num_processes=2, process_id=5)

    def test_missing_process_id_raises(self, tmp_path, monkeypatch):
        # Hostfile implies 2 processes; without a per-host process_id every
        # host would claim rank 0 and hang the rendezvous — must raise.
        for var in ("MPIT_PROCESS_ID", "MPIT_COORDINATOR",
                    "MPIT_NUM_PROCESSES"):
            monkeypatch.delenv(var, raising=False)
        p = tmp_path / "hosts"
        p.write_text("a:1\nb:1\n")
        with pytest.raises(ValueError, match="process_id required"):
            bootstrap(hostfile=str(p))

    def test_hostfile_env_resolution(self, tmp_path, monkeypatch):
        p = tmp_path / "hosts"
        p.write_text("me:1\nyou:1\n")
        monkeypatch.setenv("MPIT_HOSTFILE", str(p))
        monkeypatch.setenv("MPIT_PROCESS_ID", "3")
        # id 3 out of range for the 2-entry hostfile -> loud failure,
        # proving hostfile + env were both consulted.
        with pytest.raises(ValueError):
            bootstrap()


def test_real_group_of_one():
    """Actually form (and tear down) a num_processes=1 group — in a fresh
    subprocess, because distributed init must precede backend init and
    this test process has long since touched jax."""
    import os
    import subprocess
    import sys

    code = (
        "from mpit_tpu.parallel import bootstrap\n"
        "from mpit_tpu.parallel.distributed import shutdown\n"
        "pg = bootstrap(coordinator='localhost:12357', num_processes=1,"
        " process_id=0)\n"
        "assert pg.num_processes == 1 and pg.process_id == 0\n"
        "assert len(pg.devices) >= 1\n"
        "shutdown()\n"
        "print('GROUP OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "GROUP OK" in proc.stdout
