"""Pallas ops vs their jnp references (interpret mode on the CPU suite).

The reference frames its "unit tests" as runnable scripts checked by eye
(SURVEY.md §4); here every kernel is pinned to a pure-jnp reference
implementation with tolerances, the golden-value style the rebuild's test
strategy mandates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.ops import (
    as_rows,
    attention_reference,
    block_attention_partial,
    flash_attention,
    from_rows,
    fused_adam,
    fused_adam_reference,
    fused_elastic,
    fused_elastic_reference,
    fused_nesterov_commit,
    fused_nesterov_commit_reference,
)
from mpit_tpu.ops.flash_attention import finalize_partials, merge_partials
from mpit_tpu.optim.rules import adam_apply, adam_init


@pytest.mark.parametrize("n", [7, 128, 1024, 5000])
def test_tiles_roundtrip(rng, n):
    x = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    tiled, m = as_rows(x)
    assert tiled.ndim == 2 and tiled.shape[1] == 128
    np.testing.assert_array_equal(np.asarray(from_rows(tiled, m)), np.asarray(x))


@pytest.mark.parametrize("n", [100, 33000])
@pytest.mark.parametrize("l2wd", [0.0, 0.01])
def test_fused_nesterov(rng, n, l2wd):
    w, vt, g = (jnp.asarray(rng.normal(size=(n,)), jnp.float32) for _ in range(3))
    clr = jnp.float32(0.05)
    w1, vt1 = fused_nesterov_commit(w, vt, g, clr, l2wd=l2wd)
    w2, vt2 = fused_nesterov_commit_reference(w, vt, g, clr, l2wd=l2wd)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vt1), np.asarray(vt2), rtol=1e-5, atol=1e-6)


def test_fused_nesterov_jit_traced_lr(rng):
    w, vt, g = (jnp.asarray(rng.normal(size=(500,)), jnp.float32) for _ in range(3))

    @jax.jit
    def step(w, vt, g, clr):
        return fused_nesterov_commit(w, vt, g, clr)

    w1, vt1 = step(w, vt, g, jnp.float32(0.1))
    w2, vt2 = fused_nesterov_commit_reference(w, vt, g, 0.1)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vt1), np.asarray(vt2), rtol=1e-5, atol=1e-6)


def test_fused_adam_matches_rule(rng):
    """Kernel + external bias-correction == optim.rules adam_apply."""
    n = 2000
    p = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    st = adam_init(p)
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8
    p_ref, st_ref = p, st
    p_k, m_k, v_k, t = p, st["m"], st["v"], 0
    for _ in range(3):
        g = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
        p_ref, st_ref = adam_apply(
            p_ref, g, st_ref, lr=lr, beta1=b1, beta2=b2, epsilon=eps
        )
        t += 1
        lr_t = lr * np.sqrt(1.0 - b2**t) / (1.0 - b1**t)
        p_k, m_k, v_k = fused_adam(
            p_k, g, m_k, v_k, lr_t, beta1=b1, beta2=b2, epsilon=eps
        )
    np.testing.assert_allclose(np.asarray(p_k), np.asarray(p_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m_k), np.asarray(st_ref["m"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v_k), np.asarray(st_ref["v"]), rtol=1e-5, atol=1e-6)
    ref = fused_adam_reference(p, g, st["m"], st["v"], lr)
    assert all(r.shape == p.shape for r in ref)


def test_fused_elastic(rng):
    n = 3000
    w = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    w1, sug1 = fused_elastic(w, c, 0.15)
    w2, sug2 = fused_elastic_reference(w, c, 0.15)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sug1), np.asarray(sug2), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------


def _qkv(rng, shape):
    return tuple(
        jnp.asarray(rng.normal(size=shape) * 0.5, jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(64, 16), (2, 3, 40, 24)])
def test_flash_matches_reference(rng, causal, shape):
    q, k, v = _qkv(rng, shape)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=128)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_offsets_match_slicing(rng):
    """Offset-masked chunk attention == the matching slice of global
    causal attention (the ring-attention contract)."""
    L, D, C = 32, 16, 8
    q, k, v = _qkv(rng, (L, D))
    full = attention_reference(q, k, v, causal=True)
    for qi in range(L // C):
        parts = [
            block_attention_partial(
                q[qi * C:(qi + 1) * C], k[kj * C:(kj + 1) * C],
                v[kj * C:(kj + 1) * C], causal=True,
                q_offset=qi * C, kv_offset=kj * C,
            )
            for kj in range(L // C)
        ]
        acc, m, l = parts[0]
        for p in parts[1:]:
            acc, m, l = merge_partials((acc, m, l), p)
        merged = finalize_partials(acc, l)
        np.testing.assert_allclose(
            np.asarray(merged), np.asarray(full[qi * C:(qi + 1) * C]), atol=2e-5
        )


def test_flash_offsets_pallas(rng):
    """The pallas kernel honors traced offsets (chunk vs global slice)."""
    L, D, C = 32, 16, 16
    q, k, v = _qkv(rng, (L, D))
    full = attention_reference(q, k, v, causal=True)
    out = flash_attention(
        q[C:], k, v, causal=True, q_offset=jnp.int32(C), block_q=16, block_k=128
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(full[C:]), atol=2e-5)


def _assert_flash_grads_match(q, k, v, fa=None, atol=3e-5):
    """Shared grad check: squared-sum loss through the pallas path vs the
    dense reference, 3e-5 atol (the ONE place the loss/tolerance live).
    ``fa`` overrides the attention callable (default: tiny blocks)."""
    if fa is None:
        import functools

        fa = functools.partial(flash_attention, block_q=8, block_k=128)
    fa_loss = lambda q, k, v: jnp.sum(fa(q, k, v, causal=True) ** 2)
    ref = lambda q, k, v: jnp.sum(
        attention_reference(q, k, v, causal=True) ** 2
    )
    for a, b in zip(jax.grad(fa_loss, argnums=(0, 1, 2))(q, k, v),
                    jax.grad(ref, argnums=(0, 1, 2))(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol)
    return fa_loss, ref


@pytest.fixture
def fa_backward_path(request, monkeypatch):
    """Pin the backward schedule (fused vs two-kernel) for one test.

    The MPIT_FA_FUSED_BWD gate is read at trace time, so a cached trace
    from the other leg would silently shadow the pinned one — clear
    jax's trace/compile caches around the leg (cheap at these shapes)."""
    monkeypatch.setenv("MPIT_FA_FUSED_BWD", request.param)
    jax.clear_caches()
    yield request.param
    jax.clear_caches()


@pytest.mark.parametrize("fa_backward_path", ["1", "0"], indirect=True,
                         ids=["fused-bwd", "two-kernel-bwd"])
def test_flash_grad_matches_reference(rng, fa_backward_path):
    _assert_flash_grads_match(*_qkv(rng, (24, 16)))


def test_fwd_long_bq_block_routing(monkeypatch):
    """Length-aware forward default (KERNEL_BENCH §0.5 A/B): block_q
    grows to 2048 at Lq >= 16384 bf16 — forward only, explicit blocks
    and the env kill-switch win, f32 keeps its 512 default."""
    from mpit_tpu.ops.flash_attention import _tile_dims

    def bq_of(lq, dtype=jnp.bfloat16, fwd=True, block_q=None, **env):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        out = _tile_dims(lq, lq, 128, block_q, None, None, dtype,
                         fwd_long_bq=fwd)
        monkeypatch.delenv("MPIT_FA_LONG_BQ", raising=False)
        return out[1]

    assert bq_of(8192) == 1024          # short: flat default
    assert bq_of(16384) == 2048         # long forward: grown
    assert bq_of(32768) == 2048
    assert bq_of(32768, fwd=False) == 1024          # backward: unchanged
    assert bq_of(32768, block_q=1024) == 1024       # explicit wins
    assert bq_of(32768, MPIT_FA_LONG_BQ="0") == 1024  # env kill-switch
    assert bq_of(32768, dtype=jnp.float32) == 512   # f32 path untouched


def test_bwd_long_bk_block_routing(monkeypatch):
    """Backward default block_k grows to 2048 at Lk >= 32768 bf16 (the
    32k sweep's winner, KERNEL_BENCH §0.5) — and the fused-schedule gate
    resolves the SAME bk, so its dQ-partials transient estimate matches
    the schedule that actually runs (2 GB at 32k on the bench shape,
    admitted by the 2048 MB budget)."""
    from mpit_tpu.ops.flash_attention import _tile_dims, _use_fused_bwd

    def bk_of(lk, dtype=jnp.bfloat16, block_k=None, **env):
        for kk, vv in env.items():
            monkeypatch.setenv(kk, vv)
        out = _tile_dims(lk, lk, 128, None, block_k, None, dtype,
                         bwd_long_bk=True)
        monkeypatch.delenv("MPIT_FA_LONG_BK_BWD", raising=False)
        return out[2]

    assert bk_of(16384) == 1024          # jitter-neutral length: flat
    assert bk_of(32768) == 2048          # measured winner
    assert bk_of(32768, block_k=1024) == 1024        # explicit wins
    assert bk_of(32768, MPIT_FA_LONG_BK_BWD="0") == 1024
    assert bk_of(32768, dtype=jnp.float32) == 512

    # Gate/kernel agreement at 32k: bk=2048 -> 16 kv blocks -> exactly
    # 2048 MB on the (1, 8) x 32k x 128 bench shape -> fused admitted.
    monkeypatch.delenv("MPIT_FA_FUSED_BWD", raising=False)
    monkeypatch.delenv("MPIT_FA_FUSED_BWD_MAX_MB", raising=False)
    args32 = ((1, 8, 32768, 128), (1, 8, 32768, 128), 128, jnp.bfloat16,
              None, None, None)
    assert _use_fused_bwd(*args32) is True
    # The kill-switch restores the flat bk -> 4 GB -> two-kernel.
    monkeypatch.setenv("MPIT_FA_LONG_BK_BWD", "0")
    assert _use_fused_bwd(*args32) is False


def test_vmem_pin_keeps_flat_block_defaults(monkeypatch):
    """ADVICE round-5 regression: MPIT_FA_VMEM_MB=0 (the stock-budget
    A/B control) suppresses the auto VMEM raise, so the length-aware
    2048-block defaults — whose >4 MB score tile cannot compile under
    the stock 16 MB budget — must fall back to the flat 1024 blocks.
    Any explicit budget below the 64 MB floor pins the same fallback; a
    budget at/above it (and the unset default) keeps the grown tiles."""
    from mpit_tpu.ops.flash_attention import _tile_dims

    def blocks_of(**env):
        monkeypatch.delenv("MPIT_FA_VMEM_MB", raising=False)
        for kk, vv in env.items():
            monkeypatch.setenv(kk, vv)
        fwd = _tile_dims(32768, 32768, 128, None, None, None, jnp.bfloat16,
                         fwd_long_bq=True)
        bwd = _tile_dims(32768, 32768, 128, None, None, None, jnp.bfloat16,
                         bwd_long_bk=True)
        return fwd[1], bwd[2]

    assert blocks_of() == (2048, 2048)  # unset: length-aware defaults
    # The documented control combination (ADVICE: flash_attention.py
    # _fa_compiler_params) now resolves a compilable geometry.
    assert blocks_of(MPIT_FA_VMEM_MB="0") == (1024, 1024)
    assert blocks_of(MPIT_FA_VMEM_MB="16") == (1024, 1024)  # below floor
    assert blocks_of(MPIT_FA_VMEM_MB="64") == (2048, 2048)  # at floor
    assert blocks_of(MPIT_FA_VMEM_MB="100") == (2048, 2048)
    # Explicit block sizes are never second-guessed by the pin.
    out = _tile_dims(32768, 32768, 128, 2048, None, None, jnp.bfloat16,
                     fwd_long_bq=True)
    assert out[1] == 2048
    monkeypatch.delenv("MPIT_FA_VMEM_MB", raising=False)


@pytest.mark.parametrize("fa_backward_path", ["1", "0"], indirect=True,
                         ids=["fused-bwd", "two-kernel-bwd"])
@pytest.mark.parametrize("blocks", [(1024, 2048)])
def test_flash_grad_matches_reference_wide_bk(rng, blocks, fa_backward_path):
    """Multi-block bk=2048 geometry (the long-L backward default),
    exercised in interpret mode at a size with >=2 kv blocks per grid —
    small-shape grad tests clamp blocks and never see this shape."""
    bq, bk = blocks
    L = 4096
    q, k, v = _qkv(rng, (L, 64))

    import functools
    from mpit_tpu.ops import flash_attention

    fa = functools.partial(flash_attention, block_q=bq, block_k=bk)
    _assert_flash_grads_match(q, k, v, fa=fa)


def test_fused_bwd_auto_gate(monkeypatch):
    """The auto mode picks the fused sweep only while the dQ-partials
    transient (batch x n_kv_blocks x Lq_p x D_p f32) fits the budget."""
    from mpit_tpu.ops.flash_attention import _use_fused_bwd

    monkeypatch.delenv("MPIT_FA_FUSED_BWD", raising=False)
    # 8k, 8 heads, bf16 1024-blocks: 8 * 8 * 8192 * 128 * 4 = 256 MB.
    args = ((1, 8, 8192, 128), (1, 8, 8192, 128), 128, jnp.bfloat16,
            None, None, None)
    assert _use_fused_bwd(*args) is True  # default budget 2048 MB
    monkeypatch.setenv("MPIT_FA_FUSED_BWD_MAX_MB", "255")
    assert _use_fused_bwd(*args) is False
    monkeypatch.delenv("MPIT_FA_FUSED_BWD_MAX_MB", raising=False)
    # 16k, 8 heads: 16 * 16384 * 128 * 4 x 8 = 1 GB — admitted by the
    # round-5 budget (the on-chip A/B measured fused 5.7% faster here;
    # KERNEL_BENCH §0.6).
    args16 = ((1, 8, 16384, 128), (1, 8, 16384, 128), 128, jnp.bfloat16,
              None, None, None)
    assert _use_fused_bwd(*args16) is True
    # 32k: the length-aware backward default bk=2048 (16 kv blocks)
    # puts the transient at exactly 2048 MB -> admitted; pinning the
    # flat bk=1024 (32 blocks, 4 GB) or shaving the budget refuses it.
    args32 = ((1, 8, 32768, 128), (1, 8, 32768, 128), 128, jnp.bfloat16,
              None, None, None)
    monkeypatch.delenv("MPIT_FA_FUSED_BWD_MAX_MB", raising=False)
    assert _use_fused_bwd(*args32) is True
    monkeypatch.setenv("MPIT_FA_FUSED_BWD_MAX_MB", "2047")
    assert _use_fused_bwd(*args32) is False
    monkeypatch.delenv("MPIT_FA_FUSED_BWD_MAX_MB", raising=False)
    args32_flat = args32[:-1]
    assert _use_fused_bwd(*args32_flat, 1024) is False
    # The explicit levers stay unconditional.
    monkeypatch.setenv("MPIT_FA_FUSED_BWD", "1")
    assert _use_fused_bwd(*args32) is True
    monkeypatch.setenv("MPIT_FA_FUSED_BWD", "0")
    assert _use_fused_bwd(*args) is False
    # Unknown values fail loudly (pre-r5 semantics force-fused on any
    # non-"0" string — silent reinterpretation would corrupt A/Bs).
    monkeypatch.setenv("MPIT_FA_FUSED_BWD", "true")
    with pytest.raises(ValueError, match="MPIT_FA_FUSED_BWD"):
        _use_fused_bwd(*args)


def test_fused_bwd_auto_gate_end_to_end(rng, monkeypatch):
    """auto mode over budget must route a REAL vmapped grad through the
    two-kernel schedule and still match the reference — the gate's
    integration path, not just its arithmetic."""
    from mpit_tpu.ops.flash_attention import _use_fused_bwd

    monkeypatch.delenv("MPIT_FA_FUSED_BWD", raising=False)
    monkeypatch.setenv("MPIT_FA_FUSED_BWD_MAX_MB", "0.0001")
    jax.clear_caches()
    try:
        q, k, v = _qkv(rng, (2, 24, 16))
        # Pin the ROUTING first: with this budget the gate must pick the
        # two-kernel schedule for exactly this call's shapes — without
        # this, a gate regression (auto always fused) would still pass
        # the numeric check below, since both schedules are correct.
        assert _use_fused_bwd(q.shape, k.shape, q.shape[-1], q.dtype,
                              None, 8, 128) is False
        _assert_flash_grads_match(q, k, v)
    finally:
        jax.clear_caches()


def test_flash_dimsem_off_smoke(rng, monkeypatch):
    """MPIT_FA_DIMSEM=0 (unannotated grids, the other A/B lever) still
    produces correct forward and gradients."""
    monkeypatch.setenv("MPIT_FA_DIMSEM", "0")
    jax.clear_caches()
    try:
        q, k, v = _qkv(rng, (24, 16))
        fa, ref = _assert_flash_grads_match(q, k, v)
        np.testing.assert_allclose(
            float(fa(q, k, v)), float(ref(q, k, v)), rtol=1e-5
        )
    finally:
        jax.clear_caches()


def test_flash_ragged_lengths(rng):
    """Non-block-multiple Lq/Lk/D are padded and masked correctly."""
    q, k, v = _qkv(rng, (19, 12))
    k2, v2 = k[:13], v[:13]
    out = flash_attention(q, k2, v2, block_q=8, block_k=128)
    ref = attention_reference(q, k2, v2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("fa_backward_path", ["1", "0"], indirect=True,
                         ids=["fused-bwd", "two-kernel-bwd"])
def test_flash_bwd_ragged_offset_pair(rng, fa_backward_path):
    """The pallas backward handles the ring's per-step shape: unequal
    ragged Lq/Lk, global offsets, batched leading axes."""
    q = _qkv(rng, (2, 19, 12))[0]
    k, v = (x[:, :13] for x in _qkv(rng, (2, 29, 12))[:2])
    g = jnp.asarray(rng.normal(size=(2, 19, 12)), jnp.float32)
    fa = lambda q, k, v: flash_attention(
        q, k, v, causal=True, q_offset=26, kv_offset=13,
        block_q=8, block_k=128,
    )
    ref = lambda q, k, v: attention_reference(
        q, k, v, causal=True, q_offset=26, kv_offset=13
    )
    o1, vjp1 = jax.vjp(fa, q, k, v)
    o2, vjp2 = jax.vjp(ref, q, k, v)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=2e-5)
    for a, b, nm in zip(vjp1(g), vjp2(g), "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, err_msg=f"d{nm}"
        )


def test_flash_bwd_no_quadratic_intermediate():
    """The backward must never materialize an (Lq, Lk) array — the memory
    property flash attention exists for (VERDICT r2 missing-item #2).
    Audited on the jaxpr: every intermediate stays below Lq*Lk elements."""
    L, D = 4096, 64

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=256, block_k=512)
            ** 2
        )

    spec = jax.ShapeDtypeStruct((L, D), jnp.float32)
    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(spec, spec, spec)

    def walk(jx):
        for eqn in jx.eqns:
            for var in eqn.outvars:
                size = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                assert size < L * L, (
                    f"quadratic intermediate {var.aval.shape} from {eqn.primitive}"
                )
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)

    walk(jaxpr.jaxpr)


class TestFusedRouting:
    """The opt-in wiring: rules/msgd route through the pallas kernels and
    match the plain-XLA path bit-for-bit (interpret mode on CPU)."""

    def test_adam_rule_fused_matches(self, rng):
        from mpit_tpu.optim import rules

        p0 = jnp.asarray(rng.normal(size=(300,)), jnp.float32)
        gs = [jnp.asarray(rng.normal(size=(300,)), jnp.float32) for _ in range(3)]
        outs = []
        for fused in (False, True):
            rule = rules.make("adam", lr=1e-2, use_fused=fused)
            p, st = p0, rule.init(p0)
            for g in gs:
                p, st = rule.apply(p, g, st)
            outs.append(np.asarray(p))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)

    def test_msgd_fused_matches(self, rng):
        from mpit_tpu.optim.msgd import MSGDConfig, msgd_init, msgd_step

        w0 = jnp.asarray(rng.normal(size=(257,)), jnp.float32)
        xs = [jnp.asarray(rng.normal(size=(257,)), jnp.float32) for _ in range(4)]

        def vgf(w, target):
            return 0.5 * jnp.sum((w - target) ** 2), w - target

        outs = []
        for fused in (False, True):
            cfg = MSGDConfig(lr=0.05, mom=0.9, l2wd=1e-3, use_fused=fused)
            w, st = w0, msgd_init(w0)
            for t in xs:
                w, st, _ = msgd_step(vgf, w, st, cfg, t)
            outs.append(np.asarray(w))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)

    def test_resolution_order(self, monkeypatch):
        from mpit_tpu.ops.fused_update import fused_enabled

        # Explicit flag is a hard constraint and beats the env (mesh
        # trainers force False inside sharded jits).
        monkeypatch.setenv("MPIT_FUSED", "1")
        assert fused_enabled(False) is False
        monkeypatch.setenv("MPIT_FUSED", "0")
        assert fused_enabled(True) is True
        # Env applies to the unconstrained (None) sites.
        assert fused_enabled(None) is False
        monkeypatch.setenv("MPIT_FUSED", "1")
        assert fused_enabled(None) is True
