"""TcpTransport: the cross-host wire, exercised on localhost — contract
parity with the shm transport (roundtrip, FIFO, tags, size mismatch,
zero-byte header/ack), a real cross-process run, and the full PS stack
over TCP."""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mpit_tpu.comm.tcp import TcpTransport, allocate_local_addresses

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_mesh_transports(n):
    addrs, socks = allocate_local_addresses(n)
    out = [None] * n

    def build(r):
        out[r] = TcpTransport(r, n, addrs, listener=socks[r])

    # Construction blocks on the full-mesh rendezvous: run concurrently.
    threads = [threading.Thread(target=build, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(o is not None for o in out), "mesh construction hung"
    return out


@pytest.fixture
def pair():
    a, b = make_mesh_transports(2)
    yield a, b
    a.close()
    b.close()


class TestTcpTransport:
    def test_roundtrip_array(self, pair):
        a, b = pair
        data = np.arange(64, dtype=np.float32)
        a.send(data, 1, 3)
        out = np.zeros_like(data)
        b.recv(0, 3, out=out)
        np.testing.assert_array_equal(out, data)

    def test_payload_without_buffer(self, pair):
        a, b = pair
        a.send(b"over-the-wire", 1, 9)
        while not b.iprobe(0, 9):
            pass
        assert b.recv(0, 9) == b"over-the-wire"

    def test_zero_byte_header_ack(self, pair):
        a, b = pair
        a.send(b"", 1, 5)
        while not b.iprobe(0, 5):
            pass
        assert b.recv(0, 5) == b""

    def test_fifo_per_channel(self, pair):
        a, b = pair
        for i in range(5):
            a.send(np.full(4, i, np.int32), 1, 7)
        for i in range(5):
            out = np.zeros(4, np.int32)
            b.recv(0, 7, out=out)
            assert out[0] == i

    def test_tag_isolation(self, pair):
        a, b = pair
        a.send(np.full(2, 1.0, np.float32), 1, 11)
        a.send(np.full(2, 2.0, np.float32), 1, 22)
        out22 = np.zeros(2, np.float32)
        b.recv(0, 22, out=out22)  # later tag first
        assert out22[0] == 2.0
        out11 = np.zeros(2, np.float32)
        b.recv(0, 11, out=out11)
        assert out11[0] == 1.0

    def test_size_mismatch_raises_and_message_survives(self, pair):
        a, b = pair
        a.send(np.zeros(8, np.float32), 1, 4)
        while not b.iprobe(0, 4):
            pass
        small = np.zeros(2, np.float32)
        h = b.irecv(0, 4, out=small)
        with pytest.raises(ValueError, match="size mismatch"):
            b.test(h)
        # The message is still deliverable to a right-sized buffer.
        ok = np.ones(8, np.float32)
        b.recv(0, 4, out=ok)
        assert (ok == 0).all()

    def test_cancel_releases(self, pair):
        a, b = pair
        h = b.irecv(0, 99)
        b.cancel(h)
        assert h.cancelled and not b.test(h)

    def test_large_message(self, pair):
        a, b = pair
        data = np.random.default_rng(0).normal(size=1 << 20).astype(np.float32)
        h = a.isend(data, 1, 2)
        out = np.zeros_like(data)
        b.recv(0, 2, out=out)
        while not a.test(h):
            pass
        np.testing.assert_array_equal(out, data)

    def test_outbox_is_zero_copy_and_nonblocking(self, pair):
        # A deep backlog must not snapshot payloads (O(1) transport-owned
        # memory per queued message) and isend must stay nonblocking.
        # Stall b's reader (its frame loop needs b._lock) so TCP
        # backpressure provably retains entries in a's outbox.
        a, b = pair
        payload = np.arange(1 << 18, dtype=np.float32)  # 1 MiB each
        with b._lock:
            handles = [a.isend(payload, 1, 5) for _ in range(8)]
            with a._out_cv[1]:
                entries = list(a._outboxes[1])
        assert entries, "outbox must retain entries while the peer stalls"
        assert all(isinstance(e[2], memoryview) for e in entries)
        outs = [np.zeros_like(payload) for _ in range(8)]
        for out in outs:
            b.recv(0, 5, out=out)
        for h in handles:
            while not a.test(h):
                pass
        for out in outs:
            np.testing.assert_array_equal(out, payload)

    def test_isend_to_dead_peer_cancels_and_raises_once(self, pair):
        a, b = pair
        a._drain_outbox(1, error="rank 1 connection lost")
        h = a.isend(np.arange(4, dtype=np.float32), 1, 6)
        assert h.cancelled and not h.done
        with pytest.raises(RuntimeError, match="unreachable"):
            a.test(h)
        assert a.test(h) is False  # raise-once, then quiet not-done

    def test_peer_crash_fails_blocked_recvs(self):
        """A mid-run peer death must fail pending receives loudly (the
        raise-once convention), not leave them polling forever; messages
        delivered before the crash still serve matching receives."""
        a, b = make_mesh_transports(2)
        try:
            # One message lands before the crash...
            hs = b.isend(np.arange(3, dtype=np.float32), 0, 7)
            deadline = time.monotonic() + 10
            while not a.iprobe(1, 7):
                assert time.monotonic() < deadline, "delivery hung"
            assert b.test(hs)
            # ...then rank 1 dies (simulated: close without orderly flag).
            for conn in b._peers.values():
                conn.shutdown(socket.SHUT_RDWR)
            h_served = a.irecv(1, 7, out=np.empty(3, np.float32))
            h_starved = a.irecv(1, 7, out=np.empty(3, np.float32))
            deadline = time.monotonic() + 10
            while not a.test(h_served):
                assert time.monotonic() < deadline, "backlog recv hung"
            # The starved recv fails loudly once the reader notices.
            deadline = time.monotonic() + 10
            while True:
                try:
                    assert not a.test(h_starved)
                except RuntimeError as e:
                    assert "connection lost" in str(e)
                    break
                assert time.monotonic() < deadline, "starved recv never failed"
            # New receives from the dead peer fail immediately.
            h_new = a.irecv(1, 9)
            with pytest.raises(RuntimeError, match="connection lost"):
                a.test(h_new)
            # Probe loops (the aio probe-then-recv pattern) fail loudly
            # too once the channel is drained.
            with pytest.raises(RuntimeError, match="connection lost"):
                a.iprobe(1, 11)
        finally:
            a.close()
            b.close()

    def test_graceful_close_keeps_old_silent_semantics(self):
        """An orderly close() announces itself (goodbye frame): the
        surviving side's probes/recvs must NOT raise connection-lost —
        that convention is reserved for crashes.  This is the normal PS
        teardown order (a client finishes and closes while the server
        still serves)."""
        a, b = make_mesh_transports(2)
        try:
            b.close()
            # The reader consumes the goodbye asynchronously (its thread
            # exits when it does — observable via the role-named thread);
            # probes stay quietly False throughout, and the wait below is
            # REQUIRED to observe consumption, so the post-goodbye asserts
            # can never pass vacuously.  Common case: milliseconds.
            deadline = time.monotonic() + 5
            consumed = False
            while time.monotonic() < deadline and not consumed:
                assert a.iprobe(1, 7) is False
                consumed = not any(
                    t.is_alive() and t.name.startswith("_reader")
                    for t in a._threads
                )
                time.sleep(0.02)
            assert consumed, "goodbye never consumed within 5s"
            assert a.iprobe(1, 7) is False
            h = a.irecv(1, 7, out=np.empty(1, np.float32))
            assert a.test(h) is False  # pending, not poisoned
            a.cancel(h)
        finally:
            a.close()

    def test_close_cancels_queued_sends(self):
        """No orphaned handles: after close every send handle is done or
        cancelled (a blocking sender must not spin forever), and isend on
        a closed transport raises."""
        a, b = make_mesh_transports(2)
        hs = [a.isend(np.zeros(4, np.float32), 1, 1) for _ in range(3)]
        a.close()
        b.close()
        assert all(h.done or h.cancelled for h in hs)
        with pytest.raises(RuntimeError, match="closed"):
            a.isend(b"x", 1, 1)

    def test_invalid_rank(self, pair):
        a, _ = pair
        with pytest.raises(ValueError):
            a.isend(b"x", 0, 1)  # self
        with pytest.raises(ValueError):
            a.irecv(5, 1)


class TestPSOverTcp:
    def test_downpour_end_to_end(self, rng):
        """Full PS stack over TCP sockets matches serial SGD — the
        cross-host deployment shape on localhost."""
        import jax.numpy as jnp

        from mpit_tpu.optim.downpour import Downpour
        from mpit_tpu.ps import ParamClient, ParamServer

        transports = make_mesh_transports(3)
        w0 = rng.normal(size=10).astype(np.float32)
        lr, steps = 0.1, 4
        servers = [
            ParamServer(r, [2], transports[r], rule="add") for r in (0, 1)
        ]
        sthreads = [threading.Thread(target=s.start, daemon=True) for s in servers]
        for t in sthreads:
            t.start()
        client = ParamClient(2, [0, 1], transports[2], seed_servers=True)

        def vgf(w, target):
            return 0.5 * jnp.sum((w - target) ** 2), w - target

        opt = Downpour(vgf, client, lr=lr, su=1)
        w = opt.start(jnp.asarray(w0))
        for _ in range(steps):
            w, _ = opt.step(w, jnp.zeros(10))
        opt.stop()
        for t in sthreads:
            t.join(20)
            assert not t.is_alive()
        for tr in transports:
            tr.close()

        ref = w0.astype(np.float64)
        for _ in range(steps):
            ref = ref - lr * ref
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)


class TestPSOverFlakyTcp:
    def test_downpour_survives_mid_training_tear(self, rng):
        """The full PS stack over a FLAKY link: a client<->server socket
        is torn mid-training with reconnect enabled — the exactly-once
        transport layer makes the optimizer trajectory identical to the
        healthy run (no lost push, no duplicated grad apply)."""
        import jax.numpy as jnp

        from mpit_tpu.optim.downpour import Downpour
        from mpit_tpu.ps import ParamClient, ParamServer

        addrs, socks = allocate_local_addresses(3)
        out = [None] * 3

        def build(r):
            out[r] = TcpTransport(r, 3, addrs, listener=socks[r],
                                  reconnect=20.0)

        ts = [threading.Thread(target=build, args=(r,)) for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        transports = out
        w0 = rng.normal(size=10).astype(np.float32)
        lr, steps = 0.1, 6
        servers = [
            ParamServer(r, [2], transports[r], rule="add") for r in (0, 1)
        ]
        sthreads = [threading.Thread(target=s.start, daemon=True)
                    for s in servers]
        for t in sthreads:
            t.start()
        client = ParamClient(2, [0, 1], transports[2], seed_servers=True)

        def vgf(w, target):
            return 0.5 * jnp.sum((w - target) ** 2), w - target

        opt = Downpour(vgf, client, lr=lr, su=1)
        w = opt.start(jnp.asarray(w0))
        for step in range(steps):
            if step == 2:  # tear the client<->server-0 link mid-run
                try:
                    transports[2]._peers[0].shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            w, _ = opt.step(w, jnp.zeros(10))
        opt.stop()
        for t in sthreads:
            t.join(30)
            assert not t.is_alive()
        for tr in transports:
            tr.close()

        ref = w0.astype(np.float64)
        for _ in range(steps):
            ref = ref - lr * ref
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)


class TestCrossProcess:
    def test_echo_between_processes(self, tmp_path):
        """Two real OS processes over TCP — the cross-host shape."""
        addrs, socks = allocate_local_addresses(2)
        for s in socks:  # children bind their own listeners on these ports
            s.close()
        code = """
import sys
import numpy as np
from mpit_tpu.comm.tcp import TcpTransport

rank = int(sys.argv[1])
addrs = sys.argv[2].split(",")
t = TcpTransport(rank, 2, addrs, connect_timeout=30)
if rank == 0:
    data = np.arange(16, dtype=np.float32)
    t.send(data, 1, 1)
    out = np.zeros(16, np.float32)
    t.recv(1, 2, out=out)
    assert (out == data * 2).all()
    print("RANK0 OK")
else:
    out = np.zeros(16, np.float32)
    t.recv(0, 1, out=out)
    t.send(out * 2, 0, 2)
    print("RANK1 OK")
t.close()
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", code, str(r), ",".join(addrs)],
                cwd=REPO, env=env, stdout=subprocess.PIPE, text=True,
            )
            for r in range(2)
        ]
        outs = [p.communicate(timeout=60)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs
        assert "RANK0 OK" in outs[0] and "RANK1 OK" in outs[1]


@pytest.mark.slow
class TestGangOverTcp:
    def test_mnist_gang_tcp(self):
        """np=2 launcher gang wired over TCP instead of shm."""
        from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

        addrs, socks = allocate_local_addresses(2)
        for s in socks:
            s.close()  # children re-bind these ports
        cfg = LAUNCH_DEFAULTS.merged(
            np=2, opt="downpour", epochs=1, model="linear", side=8,
            batch=64, transport="tcp", tcp_addrs=",".join(addrs),
        )
        results = launch_processes(cfg, timeout=600)
        assert results[1]["role"] == "worker"
        assert np.isfinite(results[1]["final_test_err"])


class TestReconnect:
    """Bounded fault recovery (reconnect > 0): torn sockets are
    re-established, in-flight frames are resent whole, duplicates are
    dropped, and a restarted rank can rejoin the mesh."""

    def _mesh(self, n, reconnect):
        addrs, socks = allocate_local_addresses(n)
        out = [None] * n

        def build(r):
            out[r] = TcpTransport(r, n, addrs, listener=socks[r],
                                  reconnect=reconnect)

        threads = [threading.Thread(target=build, args=(r,)) for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert all(o is not None for o in out), "mesh construction hung"
        return addrs, socks, out

    def test_socket_break_resends_and_dedups(self):
        _addrs, _socks, (a, b) = self._mesh(2, reconnect=15.0)
        try:
            # Warm traffic, then tear the live socket pair mid-run.
            a.send(np.arange(32, dtype=np.float32), 1, 1)
            out = np.zeros(32, np.float32)
            b.recv(0, 1, out=out)

            a._peers[1].shutdown(socket.SHUT_RDWR)  # simulate a torn link

            # Both directions must survive: frames queued before, during,
            # and after the break arrive exactly once, in order.
            sends = [a.isend(np.full(64, i, np.float32), 1, 7)
                     for i in range(8)]
            got = []
            for i in range(8):
                buf = np.zeros(64, np.float32)
                b.recv(0, 7, out=buf)
                got.append(buf[0])
            assert got == list(map(float, range(8))), got
            for h in sends:
                while not a.test(h):
                    pass
            # reverse direction over the reconnected socket
            b.send(b"back at you", 0, 9)
            assert a.recv(1, 9) == b"back at you"
        finally:
            a.close()
            b.close()

    def test_restarted_rank_rejoins(self):
        addrs, _socks, (a, b) = self._mesh(2, reconnect=15.0)
        b2 = None
        try:
            a.send(b"pre-crash", 1, 3)
            assert b.recv(0, 3) == b"pre-crash"
            # Rank 1 dies hard (no goodbye) and a fresh process takes
            # over its address: new listener on the same port, redial.
            for conn in b._peers.values():
                conn.shutdown(socket.SHUT_RDWR)
            b._closed = True  # suppress b's own recovery; it is "dead"
            b._listener.close()
            b2 = TcpTransport(1, 2, addrs, reconnect=15.0)
            # a's sends reach the replacement (nonce reset accepts the
            # restarted sequence space), and the replacement can send.
            a.send(b"hello new rank", 1, 5)
            assert b2.recv(0, 5) == b"hello new rank"
            b2.send(b"reporting in", 0, 6)
            assert a.recv(1, 6) == b"reporting in"
        finally:
            a.close()
            if b2 is not None:
                b2.close()

    def test_stale_generation_ack_is_dropped(self):
        """An ack enqueued by a reader of a superseded connection must not
        reach the outbox: after a restarted peer installs (nonce reset
        purges queued acks), a stale ack carrying the dead instance's
        sequence horizon would release the replacement's unacked window."""
        _addrs, _socks, (a, b) = self._mesh(2, reconnect=15.0)
        try:
            a.send(b"warm", 1, 2)
            assert b.recv(0, 2) == b"warm"
            with b._lock:
                old_gen = b._gen[0]
                b._gen[0] += 1  # simulate a replacement install winning
            with b._out_cv[0]:
                b._pending_ack[0] = None
                b._outboxes[0].clear()
            b._enqueue_ack(0, 10**9, old_gen)  # the racing reader's enqueue
            with b._out_cv[0]:
                assert b._pending_ack.get(0) is None
                assert not b._outboxes[0]
            with b._lock:
                b._gen[0] = old_gen  # restore so close() is orderly
        finally:
            a.close()
            b.close()

    def test_window_expiry_falls_back_to_fail_loud(self):
        _addrs, _socks, (a, b) = self._mesh(2, reconnect=0.3)
        try:
            # Kill rank 1 outright; nothing ever redials its address.
            for conn in b._peers.values():
                conn.shutdown(socket.SHUT_RDWR)
            b._closed = True
            b._listener.close()
            h = a.isend(np.zeros(8, np.float32), 1, 2)
            deadline = time.monotonic() + 10
            with pytest.raises(RuntimeError, match="connection lost"):
                while time.monotonic() < deadline:
                    if a.test(h):
                        raise AssertionError("send completed to dead rank")
                    time.sleep(0.01)
                raise TimeoutError("fail-loud never triggered")
        finally:
            a.close()
            b.close()


def test_cross_process_kill_and_resume(tmp_path):
    """A rank process dies hard (no goodbye) mid-gang and a replacement
    process rebinds its address: the surviving rank's queued frames reach
    the replacement and traffic resumes — the TCP analog of the shm
    transport's EOWNERDEAD remap."""
    addrs, socks = allocate_local_addresses(2)
    for s in socks:  # children rebind their own listeners
        s.close()
    child_src = (
        "import sys, time\n"
        "import numpy as np\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from mpit_tpu.comm.tcp import TcpTransport\n"
        "addrs = sys.argv[1].split(',')\n"
        "phase = sys.argv[2]\n"
        "t = TcpTransport(1, 2, addrs, reconnect=20.0)\n"
        "out = np.zeros(128, np.float32)\n"
        "if phase == 'first':\n"
        "    t.recv(0, 5, out=out)\n"
        "    assert out[0] == 1.0\n"
        "    time.sleep(0.2)\n"
        "    sys.exit(37)  # hard death: no goodbye, no close\n"
        "else:\n"
        "    t.recv(0, 6, out=out)  # frame queued while rank was dead\n"
        "    assert out[0] == 2.0\n"
        "    t.send(b'replacement alive', 0, 7)\n"
        "    t.close()\n"
    )
    p1 = subprocess.Popen(
        [sys.executable, "-c", child_src, ",".join(addrs), "first"])
    parent = TcpTransport(0, 2, addrs, reconnect=20.0, connect_timeout=30.0)
    try:
        parent.send(np.full(128, 1.0, np.float32), 1, 5)
        p1.wait(30)
        assert p1.returncode == 37
        h = parent.isend(np.full(128, 2.0, np.float32), 1, 6)
        p2 = subprocess.Popen(
            [sys.executable, "-c", child_src, ",".join(addrs), "second"])
        deadline = time.monotonic() + 30
        while not parent.test(h):
            assert time.monotonic() < deadline, "resend never completed"
            time.sleep(0.01)
        assert parent.recv(1, 7) == b"replacement alive"
        p2.wait(30)
        assert p2.returncode == 0
    finally:
        parent.close()


def test_reconnect_mid_burst_tear_no_loss_no_dup():
    """Tear the link while a burst is in flight (frames sitting in the
    kernel send buffer are NOT delivered — the ack protocol must resend
    them and dedup the overlap): 50 frames arrive exactly once, in
    order, and every sender handle is eventually acked."""
    addrs, socks = allocate_local_addresses(2)
    out = [None, None]

    def build(r):
        out[r] = TcpTransport(r, 2, addrs, listener=socks[r],
                              reconnect=15.0)

    ts = [threading.Thread(target=build, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    a, b = out
    try:
        def tear():
            time.sleep(0.005)
            try:
                a._peers[1].shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

        killer = threading.Thread(target=tear)
        killer.start()
        handles = [a.isend(np.full(4096, i, np.float32), 1, 7)
                   for i in range(50)]
        killer.join()
        got = []
        for _ in range(50):
            buf = np.zeros(4096, np.float32)
            b.recv(0, 7, out=buf)
            got.append(int(buf[0]))
        assert got == list(range(50)), got[:10]
        deadline = time.monotonic() + 20
        for h in handles:
            while not a.test(h):
                assert time.monotonic() < deadline, "ack never released"
                time.sleep(0.002)
    finally:
        a.close()
        b.close()
