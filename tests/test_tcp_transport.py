"""TcpTransport: the cross-host wire, exercised on localhost — contract
parity with the shm transport (roundtrip, FIFO, tags, size mismatch,
zero-byte header/ack), a real cross-process run, and the full PS stack
over TCP."""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mpit_tpu.comm.tcp import TcpTransport, allocate_local_addresses

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_mesh_transports(n):
    addrs, socks = allocate_local_addresses(n)
    out = [None] * n

    def build(r):
        out[r] = TcpTransport(r, n, addrs, listener=socks[r])

    # Construction blocks on the full-mesh rendezvous: run concurrently.
    threads = [threading.Thread(target=build, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(o is not None for o in out), "mesh construction hung"
    return out


@pytest.fixture
def pair():
    a, b = make_mesh_transports(2)
    yield a, b
    a.close()
    b.close()


class TestTcpTransport:
    def test_roundtrip_array(self, pair):
        a, b = pair
        data = np.arange(64, dtype=np.float32)
        a.send(data, 1, 3)
        out = np.zeros_like(data)
        b.recv(0, 3, out=out)
        np.testing.assert_array_equal(out, data)

    def test_payload_without_buffer(self, pair):
        a, b = pair
        a.send(b"over-the-wire", 1, 9)
        while not b.iprobe(0, 9):
            pass
        assert b.recv(0, 9) == b"over-the-wire"

    def test_zero_byte_header_ack(self, pair):
        a, b = pair
        a.send(b"", 1, 5)
        while not b.iprobe(0, 5):
            pass
        assert b.recv(0, 5) == b""

    def test_fifo_per_channel(self, pair):
        a, b = pair
        for i in range(5):
            a.send(np.full(4, i, np.int32), 1, 7)
        for i in range(5):
            out = np.zeros(4, np.int32)
            b.recv(0, 7, out=out)
            assert out[0] == i

    def test_tag_isolation(self, pair):
        a, b = pair
        a.send(np.full(2, 1.0, np.float32), 1, 11)
        a.send(np.full(2, 2.0, np.float32), 1, 22)
        out22 = np.zeros(2, np.float32)
        b.recv(0, 22, out=out22)  # later tag first
        assert out22[0] == 2.0
        out11 = np.zeros(2, np.float32)
        b.recv(0, 11, out=out11)
        assert out11[0] == 1.0

    def test_size_mismatch_raises_and_message_survives(self, pair):
        a, b = pair
        a.send(np.zeros(8, np.float32), 1, 4)
        while not b.iprobe(0, 4):
            pass
        small = np.zeros(2, np.float32)
        h = b.irecv(0, 4, out=small)
        with pytest.raises(ValueError, match="size mismatch"):
            b.test(h)
        # The message is still deliverable to a right-sized buffer.
        ok = np.ones(8, np.float32)
        b.recv(0, 4, out=ok)
        assert (ok == 0).all()

    def test_cancel_releases(self, pair):
        a, b = pair
        h = b.irecv(0, 99)
        b.cancel(h)
        assert h.cancelled and not b.test(h)

    def test_large_message(self, pair):
        a, b = pair
        data = np.random.default_rng(0).normal(size=1 << 20).astype(np.float32)
        h = a.isend(data, 1, 2)
        out = np.zeros_like(data)
        b.recv(0, 2, out=out)
        while not a.test(h):
            pass
        np.testing.assert_array_equal(out, data)

    def test_outbox_is_zero_copy_and_nonblocking(self, pair):
        # A deep backlog must not snapshot payloads (O(1) transport-owned
        # memory per queued message) and isend must stay nonblocking.
        # Stall b's reader (its frame loop needs b._lock) so TCP
        # backpressure provably retains entries in a's outbox.
        a, b = pair
        payload = np.arange(1 << 18, dtype=np.float32)  # 1 MiB each
        with b._lock:
            handles = [a.isend(payload, 1, 5) for _ in range(8)]
            with a._out_cv[1]:
                entries = list(a._outboxes[1])
        assert entries, "outbox must retain entries while the peer stalls"
        assert all(isinstance(e[2], memoryview) for e in entries)
        outs = [np.zeros_like(payload) for _ in range(8)]
        for out in outs:
            b.recv(0, 5, out=out)
        for h in handles:
            while not a.test(h):
                pass
        for out in outs:
            np.testing.assert_array_equal(out, payload)

    def test_isend_to_dead_peer_cancels_and_raises_once(self, pair):
        a, b = pair
        a._drain_outbox(1, error="rank 1 connection lost")
        h = a.isend(np.arange(4, dtype=np.float32), 1, 6)
        assert h.cancelled and not h.done
        with pytest.raises(RuntimeError, match="unreachable"):
            a.test(h)
        assert a.test(h) is False  # raise-once, then quiet not-done

    def test_peer_crash_fails_blocked_recvs(self):
        """A mid-run peer death must fail pending receives loudly (the
        raise-once convention), not leave them polling forever; messages
        delivered before the crash still serve matching receives."""
        a, b = make_mesh_transports(2)
        try:
            # One message lands before the crash...
            hs = b.isend(np.arange(3, dtype=np.float32), 0, 7)
            deadline = time.monotonic() + 10
            while not a.iprobe(1, 7):
                assert time.monotonic() < deadline, "delivery hung"
            assert b.test(hs)
            # ...then rank 1 dies (simulated: close without orderly flag).
            for conn in b._peers.values():
                conn.shutdown(socket.SHUT_RDWR)
            h_served = a.irecv(1, 7, out=np.empty(3, np.float32))
            h_starved = a.irecv(1, 7, out=np.empty(3, np.float32))
            deadline = time.monotonic() + 10
            while not a.test(h_served):
                assert time.monotonic() < deadline, "backlog recv hung"
            # The starved recv fails loudly once the reader notices.
            deadline = time.monotonic() + 10
            while True:
                try:
                    assert not a.test(h_starved)
                except RuntimeError as e:
                    assert "connection lost" in str(e)
                    break
                assert time.monotonic() < deadline, "starved recv never failed"
            # New receives from the dead peer fail immediately.
            h_new = a.irecv(1, 9)
            with pytest.raises(RuntimeError, match="connection lost"):
                a.test(h_new)
            # Probe loops (the aio probe-then-recv pattern) fail loudly
            # too once the channel is drained.
            with pytest.raises(RuntimeError, match="connection lost"):
                a.iprobe(1, 11)
        finally:
            a.close()
            b.close()

    def test_graceful_close_keeps_old_silent_semantics(self):
        """An orderly close() announces itself (goodbye frame): the
        surviving side's probes/recvs must NOT raise connection-lost —
        that convention is reserved for crashes.  This is the normal PS
        teardown order (a client finishes and closes while the server
        still serves)."""
        a, b = make_mesh_transports(2)
        try:
            b.close()
            deadline = time.monotonic() + 5
            # The reader consumes the goodbye asynchronously; probes stay
            # quietly False throughout and afterwards.
            while time.monotonic() < deadline:
                assert a.iprobe(1, 7) is False
                if 1 not in a._peers or not any(
                    t.is_alive() for t in a._threads
                ):
                    break
                time.sleep(0.02)
            assert a.iprobe(1, 7) is False
            h = a.irecv(1, 7, out=np.empty(1, np.float32))
            assert a.test(h) is False  # pending, not poisoned
            a.cancel(h)
        finally:
            a.close()

    def test_close_cancels_queued_sends(self):
        """No orphaned handles: after close every send handle is done or
        cancelled (a blocking sender must not spin forever), and isend on
        a closed transport raises."""
        a, b = make_mesh_transports(2)
        hs = [a.isend(np.zeros(4, np.float32), 1, 1) for _ in range(3)]
        a.close()
        b.close()
        assert all(h.done or h.cancelled for h in hs)
        with pytest.raises(RuntimeError, match="closed"):
            a.isend(b"x", 1, 1)

    def test_invalid_rank(self, pair):
        a, _ = pair
        with pytest.raises(ValueError):
            a.isend(b"x", 0, 1)  # self
        with pytest.raises(ValueError):
            a.irecv(5, 1)


class TestPSOverTcp:
    def test_downpour_end_to_end(self, rng):
        """Full PS stack over TCP sockets matches serial SGD — the
        cross-host deployment shape on localhost."""
        import jax.numpy as jnp

        from mpit_tpu.optim.downpour import Downpour
        from mpit_tpu.ps import ParamClient, ParamServer

        transports = make_mesh_transports(3)
        w0 = rng.normal(size=10).astype(np.float32)
        lr, steps = 0.1, 4
        servers = [
            ParamServer(r, [2], transports[r], rule="add") for r in (0, 1)
        ]
        sthreads = [threading.Thread(target=s.start, daemon=True) for s in servers]
        for t in sthreads:
            t.start()
        client = ParamClient(2, [0, 1], transports[2], seed_servers=True)

        def vgf(w, target):
            return 0.5 * jnp.sum((w - target) ** 2), w - target

        opt = Downpour(vgf, client, lr=lr, su=1)
        w = opt.start(jnp.asarray(w0))
        for _ in range(steps):
            w, _ = opt.step(w, jnp.zeros(10))
        opt.stop()
        for t in sthreads:
            t.join(20)
            assert not t.is_alive()
        for tr in transports:
            tr.close()

        ref = w0.astype(np.float64)
        for _ in range(steps):
            ref = ref - lr * ref
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)


class TestCrossProcess:
    def test_echo_between_processes(self, tmp_path):
        """Two real OS processes over TCP — the cross-host shape."""
        addrs, socks = allocate_local_addresses(2)
        for s in socks:  # children bind their own listeners on these ports
            s.close()
        code = """
import sys
import numpy as np
from mpit_tpu.comm.tcp import TcpTransport

rank = int(sys.argv[1])
addrs = sys.argv[2].split(",")
t = TcpTransport(rank, 2, addrs, connect_timeout=30)
if rank == 0:
    data = np.arange(16, dtype=np.float32)
    t.send(data, 1, 1)
    out = np.zeros(16, np.float32)
    t.recv(1, 2, out=out)
    assert (out == data * 2).all()
    print("RANK0 OK")
else:
    out = np.zeros(16, np.float32)
    t.recv(0, 1, out=out)
    t.send(out * 2, 0, 2)
    print("RANK1 OK")
t.close()
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", code, str(r), ",".join(addrs)],
                cwd=REPO, env=env, stdout=subprocess.PIPE, text=True,
            )
            for r in range(2)
        ]
        outs = [p.communicate(timeout=60)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs
        assert "RANK0 OK" in outs[0] and "RANK1 OK" in outs[1]


@pytest.mark.slow
class TestGangOverTcp:
    def test_mnist_gang_tcp(self):
        """np=2 launcher gang wired over TCP instead of shm."""
        from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

        addrs, socks = allocate_local_addresses(2)
        for s in socks:
            s.close()  # children re-bind these ports
        cfg = LAUNCH_DEFAULTS.merged(
            np=2, opt="downpour", epochs=1, model="linear", side=8,
            batch=64, transport="tcp", tcp_addrs=",".join(addrs),
        )
        results = launch_processes(cfg, timeout=600)
        assert results[1]["role"] == "worker"
        assert np.isfinite(results[1]["final_test_err"])
