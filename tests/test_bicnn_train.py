"""BiCNN trainer: feval semantics, learning, roles, distributed topologies."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.data import qa
from mpit_tpu.train.bicnn import BICNN_DEFAULTS, BiCNNTrainer, server_rule_for
from mpit_tpu.train.bicnn_launch import BICNN_LAUNCH_DEFAULTS, assign_roles, run_rank

TINY = dict(
    embedding_dim=6, word_hidden_dim=8, num_filters=10, cont_conv_width=2,
    maxnegsample=4, batch_size=8, eval_chunk=16, loss_report_every=10**9,
)


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("qa_train")
    paths = qa.synthetic_qa(d, n_labels=10, n_train=96, n_eval=16,
                            embedding_dim=6, vocab_words=60, seed=11)
    return qa.load_qa_files(embedding_dim=6, conv_width=2, **paths)


def make_trainer(data, pclient=None, rank=0, **over):
    cfg = BICNN_DEFAULTS.merged(TINY).merged(over)
    return BiCNNTrainer(cfg, pclient=pclient, data=data, rank=rank)


class TestFeval:
    def test_negative_sampling_rejects_gold(self, data):
        tr = make_trainer(data, optimization="sgd")
        labels = [data.train.labels[i] for i in range(8)]
        for _ in range(5):
            nt, nl = tr.sample_negatives(labels)
            assert nt.shape[:2] == (8, 4)
            rows_by_label = {lab: data.answer_tokens[data.label2row[lab]]
                            for lab in {l for ls in labels for l in ls}}
            for i, gold in enumerate(labels):
                for k in range(nt.shape[1]):
                    for lab in gold:
                        assert not np.array_equal(nt[i, k], rows_by_label[lab])

    def test_vgf_loss_and_grad_shapes(self, data):
        tr = make_trainer(data, optimization="sgd")
        idx = np.arange(8)
        trn = data.train
        nt, nl = tr.sample_negatives([trn.labels[i] for i in idx])
        loss, g = tr._vgf(
            tr.w, jnp.asarray(trn.q_tokens[idx]), jnp.asarray(trn.q_len[idx]),
            jnp.asarray(trn.a_tokens[idx]), jnp.asarray(trn.a_len[idx]),
            jnp.asarray(nt), jnp.asarray(nl),
        )
        assert np.isfinite(float(loss))
        assert g.shape == tr.w.shape
        assert float(jnp.max(jnp.abs(g))) <= BICNN_DEFAULTS.grad_clip + 1e-6

    def test_no_violation_means_zero_grad(self, data):
        """An example whose every candidate satisfies the margin is skipped
        (the goto-continue path, bicnn.lua:361-371) — zero loss, zero grad."""
        tr = make_trainer(data, optimization="sgd", l2reg=0.0, margin=-10.0)
        # margin=-10: s_pos - s_neg < -10 is impossible (scores in (0,1)),
        # so NO candidate ever violates -> every example skipped.
        idx = np.arange(8)
        trn = data.train
        nt, nl = tr.sample_negatives([trn.labels[i] for i in idx])
        loss, g = tr._vgf(
            tr.w, jnp.asarray(trn.q_tokens[idx]), jnp.asarray(trn.q_len[idx]),
            jnp.asarray(trn.a_tokens[idx]), jnp.asarray(trn.a_len[idx]),
            jnp.asarray(nt), jnp.asarray(nl),
        )
        assert float(loss) == 0.0
        assert float(jnp.max(jnp.abs(g))) == 0.0

    def test_reg_scales_with_contributing_examples(self, data):
        """L2 term is added once per contributing example (bicnn.lua:392-397)."""
        tr0 = make_trainer(data, optimization="sgd", l2reg=0.0, margin=0.9)
        tr2 = make_trainer(data, optimization="sgd", l2reg=1e-3, margin=0.9)
        idx = np.arange(8)
        trn = data.train
        nt, nl = tr0.sample_negatives([trn.labels[i] for i in idx])
        args = (
            jnp.asarray(trn.q_tokens[idx]), jnp.asarray(trn.q_len[idx]),
            jnp.asarray(trn.a_tokens[idx]), jnp.asarray(trn.a_len[idx]),
            jnp.asarray(nt), jnp.asarray(nl),
        )
        l0, _ = tr0._vgf(tr0.w, *args)
        l2, _ = tr2._vgf(tr2.w, *args)  # same init -> same w
        w = np.asarray(tr0.w)
        # margin=0.9 is near-unachievable in (0,1) scores: all 8 contribute
        want = float(l0) + 8 * 1e-3 * 0.5 * float(w @ w)
        np.testing.assert_allclose(float(l2), want, rtol=1e-4)


class TestDevicePoolScorer:
    def test_matches_host_loop_oracle(self, data):
        """The on-device pool scorer must count exactly what the
        reference's per-question host loop counts (bicnn.lua:426-460),
        including unknown-candidate filtering and last-max ties."""
        from mpit_tpu.train.bicnn import gesd_np

        tr = make_trainer(data, optimization="sgd")
        for name in ("valid", "test1", "test2"):
            es = getattr(tr.data, name)
            ans_emb = np.asarray(tr._embed_chunked(
                tr.w, tr.data.answer_tokens, tr.data.answer_len))
            q_emb = np.asarray(tr._embed_chunked(tr.w, es.q_tokens, es.q_len))
            l2r = tr.data.label2row
            correct = 0
            for i in range(len(es)):
                pool = [v for v in es.pools[i] if v in l2r]
                if not pool:
                    continue
                sims = gesd_np(q_emb[i], ans_emb[[l2r[v] for v in pool]])
                best_j = max(range(len(pool)), key=lambda j: (sims[j], j))
                if pool[best_j] in es.labels[i]:
                    correct += 1
            idx, mask, hit = tr._pool_tables(es, name)
            got = int(tr._pool_score(
                jnp.asarray(q_emb), jnp.asarray(ans_emb), idx, mask, hit))
            assert got == correct, name

    def test_empty_and_unknown_pools_score_zero(self, data):
        tr = make_trainer(data, optimization="sgd")
        es = tr.data.valid
        import dataclasses as dc

        broken = dc.replace(
            es, pools=[[] if i % 2 else [10**9] for i in range(len(es))]
        )
        idx, mask, hit = tr._pool_tables(broken, "broken")
        assert not bool(mask.any())
        ans_emb = tr._embed_chunked(
            tr.w, tr.data.answer_tokens, tr.data.answer_len)
        q_emb = tr._embed_chunked(tr.w, es.q_tokens, es.q_len)
        got = int(tr._pool_score(
            jnp.asarray(q_emb), jnp.asarray(ans_emb), idx, mask, hit))
        assert got == 0


class TestLocalTraining:
    def test_sgd_learns_above_chance(self, data):
        # seed pinned: the trainer's negative sampling + init are seeded
        # from cfg.seed, but XLA:CPU reduction order still wobbles the
        # trained weights across hosts/builds, and the valid split is
        # only 16 examples (one answer = 0.0625 accuracy).  The old 0.35
        # bar sat within one wobble of the typical 0.31-0.44 outcome and
        # flaked; 0.25 is still 1.5x the 1/6 chance rate, which is the
        # property under test ("learns above chance"), with the margin
        # sized to the eval set's granularity.
        tr = make_trainer(data, optimization="sgd", learning_rate=0.05,
                          momentum=0.9, epoch=15, margin=0.1, l2reg=0.0,
                          seed=1)
        result = tr.run()
        # pools have 6 candidates -> chance ~= 1/6
        assert result["accuracy"]["valid"] > 0.25
        assert result["best"]["valid"]["acc"] >= result["accuracy"]["valid"] - 1e-9

    def test_loadmodel_resume(self, data, tmp_path):
        tr = make_trainer(data, optimization="sgd",
                          outputprefix=str(tmp_path / "ck"))
        tr._save_checkpoint()
        saved = list(tmp_path.glob("ck_*.npz"))
        assert saved
        tr2 = make_trainer(data, optimization="sgd",
                           loadmodel=str(tmp_path / "ck_latest.npz"))
        np.testing.assert_allclose(np.asarray(tr2.w), np.asarray(tr.w))

    def test_comm_opt_without_pclient_raises(self, data):
        tr = make_trainer(data, optimization="downpour")
        with pytest.raises(ValueError, match="parameter client"):
            _ = tr.optimizer

    def test_preload_binary_populates_cache(self, tmp_path):
        """First preload_binary run builds + writes the cache; the second
        run loads it (plaunch.lua:218-229 analog, without checked-in files)."""
        cache = tmp_path / "qa_cache.npz"
        cfg = BICNN_DEFAULTS.merged(TINY).merged(
            preload_binary=True, binary_path=str(cache), optimization="sgd",
        )
        tr1 = BiCNNTrainer(cfg)
        assert cache.exists()
        tr2 = BiCNNTrainer(cfg)
        assert tr2.data.source.startswith("binary")
        np.testing.assert_array_equal(
            tr1.data.train.q_tokens, tr2.data.train.q_tokens
        )

    def test_explicit_file_flags(self, tmp_path):
        """All six --*_file flags take precedence over fixtures and load
        through load_qa (the plaunch.lua text-file path, plaunch.lua:45-52)."""
        paths = qa.synthetic_qa(tmp_path, n_labels=6, n_train=32, n_eval=8,
                                embedding_dim=6, vocab_words=40, seed=3)
        cfg = BICNN_DEFAULTS.merged(TINY).merged(
            optimization="sgd",
            **{k: str(p) for k, p in paths.items()},
        )
        tr = BiCNNTrainer(cfg)
        assert len(tr.data.train) == 32
        assert tr.data.vocab.embedding_dim == 6

    def test_single_process_rejects_distributed_opt(self, data):
        cfg = BICNN_LAUNCH_DEFAULTS.merged(TINY).merged(
            np=1, optimization="adamsingle", valid_mode="none",
        )
        with pytest.raises(ValueError, match="sgd"):
            run_rank(0, 1, cfg, transport=None, data=data)


class TestAssignRoles:
    def test_testerfirst(self):
        s, c, t, tr = assign_roles(7, 2, testerfirst=True)
        assert t == 0 and tr == {0}
        assert s == [2, 4, 6]  # i % 2 == 0 for i in 1..6 (plaunch.lua:126-142)
        assert c == [0, 1, 3, 5]

    def test_testerlast(self):
        s, c, t, tr = assign_roles(7, 2, testerlast=True)
        assert t == 6 and tr == {6}
        assert s == [1, 3, 5]  # (i+1) % 2 == 0 for i in 0..5 (plaunch.lua:145-160)
        assert c == [0, 2, 4, 6]

    def test_last_client_mode(self):
        s, c, t, tr = assign_roles(6, 2, valid_mode="lastClient")
        assert t is None and tr == {5}
        assert s == [0, 2, 4] and c == [1, 3, 5]

    def test_last_client_skips_server_rank(self):
        # size=7, mf=2: rank 6 is a server — the eval mark must land on the
        # last *training client* (5), not on a rank that never trains.
        s, c, t, tr = assign_roles(7, 2, valid_mode="lastClient")
        assert 6 in s and tr == {5} and 5 in c

    def test_additional_tester_requires_flag(self):
        with pytest.raises(ValueError, match="additionalTester"):
            assign_roles(6, 2, valid_mode="additionalTester")

    def test_mutually_exclusive(self):
        with pytest.raises(ValueError, match="exclusive"):
            assign_roles(6, 2, testerfirst=True, testerlast=True)

    def test_unified_tester_surface(self):
        """tester=none|first|last (the launch.py dialect) maps onto the
        plaunch booleans; conflicts between the surfaces raise."""
        from mpit_tpu.train.bicnn_launch import resolve_tester_flags

        mk = lambda **kw: BICNN_LAUNCH_DEFAULTS.merged(**kw)
        assert resolve_tester_flags(mk(tester="first")) == (True, False)
        assert resolve_tester_flags(mk(tester="last")) == (False, True)
        assert resolve_tester_flags(mk(tester="none")) == (False, False)
        # Booleans still work alone, and agreeing surfaces are fine.
        assert resolve_tester_flags(mk(testerlast=True)) == (False, True)
        assert resolve_tester_flags(
            mk(tester="last", testerlast=True)
        ) == (False, True)
        with pytest.raises(ValueError, match="conflicting"):
            resolve_tester_flags(mk(tester="first", testerlast=True))
        with pytest.raises(ValueError, match="tester must be"):
            resolve_tester_flags(mk(tester="both"))


class TestServerRule:
    def test_adam_gets_stepdiv(self):
        cfg = BICNN_DEFAULTS.merged(optimization="adam", step_div_adam=7)
        rule = server_rule_for(cfg)
        assert rule is not None  # binds without error; stepdiv path covered

    def test_delta_opts_use_add(self):
        for name in ("sgd", "downpour", "eamsgd"):
            cfg = BICNN_DEFAULTS.merged(optimization=name)
            assert server_rule_for(cfg) is not None


def run_topology(size, cfg, data, timeout=600):
    router = LocalRouter(size)
    results, errors = {}, {}

    def target(rank):
        try:
            results[rank] = run_rank(rank, size, cfg, router.endpoint(rank), data=data)
        except BaseException as exc:  # noqa: BLE001
            errors[rank] = exc

    threads = [threading.Thread(target=target, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    if errors:
        raise next(iter(errors.values()))
    assert not any(t.is_alive() for t in threads), f"hung; done={list(results)}"
    return results


@pytest.mark.slow
class TestTopologies:
    def test_downpour_np4(self, data):
        cfg = BICNN_LAUNCH_DEFAULTS.merged(TINY).merged(
            np=4, optimization="downpour", learning_rate=0.05, epoch=1,
            valid_mode="none",
        )
        results = run_topology(4, cfg, data)
        roles = {r: res["role"] for r, res in results.items()}
        assert roles == {0: "server", 1: "worker", 2: "server", 3: "worker"}
        assert all(results[r]["grads_applied"] > 0 for r in (0, 2))

    def test_eamsgd_with_tester_first(self, data, tmp_path):
        cfg = BICNN_LAUNCH_DEFAULTS.merged(TINY).merged(
            np=5, optimization="eamsgd", learning_rate=0.05, momentum=0.9,
            movingrate=0.3, commperiod=2, epoch=1,
            testerfirst=True, valid_mode="additionalTester",
            tester_rounds=2, valid_sleep_time=0.05,
            outputprefix=str(tmp_path / "bic"),
        )
        results = run_topology(5, cfg, data)
        roles = {r: res["role"] for r, res in results.items()}
        # size 5, testerfirst: tester=0, servers 2,4; workers 1,3
        assert roles == {0: "tester", 1: "worker", 2: "server",
                         3: "worker", 4: "server"}
        assert len(results[0]["history"]) == 2
        assert list(tmp_path.glob("bic_*.npz"))  # tester checkpoints

    def test_adamsingle_np3(self, data):
        cfg = BICNN_LAUNCH_DEFAULTS.merged(TINY).merged(
            np=3, optimization="adamsingle", epoch=1, valid_mode="none",
            master_freq=3,
        )
        # master_freq=3: rank 0 server, ranks 1-2 clients
        results = run_topology(3, cfg, data)
        assert results[0]["role"] == "server"

    def test_parked_rank(self, data):
        cfg = BICNN_LAUNCH_DEFAULTS.merged(TINY).merged(
            np=5, optimization="downpour", epoch=1, valid_mode="none",
            maxrank=3,
        )
        results = run_topology(5, cfg, data)
        assert results[4]["role"] == "parked"
        assert results[0]["role"] == "server"


@pytest.mark.slow
def test_docqa_real_corpus_learns_above_chance():
    """BiCNN on the committed REAL corpus (stdlib docstrings): pool size
    is 20, chance = 5%; the recorded full run (8 epochs, 200 filters)
    reaches 58-66% (docs/NORTHSTAR_r4.md) — this bounded version must
    clear 8x chance."""
    from mpit_tpu.data.qa import DOCQA_EMBEDDING_DIM, docqa_paths
    from mpit_tpu.data.qa import load_qa

    paths = docqa_paths()
    assert paths is not None, "docqa fixture missing from checkout"
    data = load_qa(embedding_dim=DOCQA_EMBEDDING_DIM, conv_width=2,
                   paths=paths)
    cfg = BICNN_DEFAULTS.merged(dict(
        optimization="sgd", learning_rate=0.05, momentum=0.9, epoch=3,
        margin=0.1, l2reg=0.0, embedding_dim=DOCQA_EMBEDDING_DIM,
        cont_conv_width=2, num_filters=100, word_hidden_dim=64,
        batch_size=16, maxnegsample=20, valid_mode="none",
        loss_report_every=10**9,
    ))
    tr = BiCNNTrainer(cfg, pclient=None, data=data, rank=0)
    res = tr.run()
    assert res["accuracy"]["valid"] > 0.4
