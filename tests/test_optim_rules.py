"""Golden-value tests for the shard-update rules and msgd.

Each rule is checked against an independent numpy re-derivation of the
reference update equations (reference BiCNN/pserver.lua:123-197,
asyncsgd/optim-msgd.lua) — not against the JAX code itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.optim import rules
from mpit_tpu.optim.msgd import MSGDConfig, msgd_init, msgd_step

RTOL = 1e-5


def rollout(rule, p0, grads):
    state = rule.init(jnp.asarray(p0))
    p = jnp.asarray(p0)
    apply = jax.jit(rule.apply)
    for g in grads:
        p, state = apply(p, jnp.asarray(g), state)
    return np.asarray(p), state


@pytest.fixture
def grads(rng):
    return [rng.normal(size=5).astype(np.float32) for _ in range(4)]


@pytest.fixture
def p0(rng):
    return rng.normal(size=5).astype(np.float32)


class TestPlainAdd:
    def test_accumulates(self, p0, grads):
        p, _ = rollout(rules.make("add"), p0, grads)
        np.testing.assert_allclose(p, p0 + sum(grads), rtol=RTOL)


class TestRMSProp:
    def test_matches_numpy(self, p0, grads):
        lr, decay, momentum, eps = 0.01, 0.9, 0.5, 1e-4
        p, _ = rollout(
            rules.make("rmsprop", lr=lr, decay=decay, momentum=momentum, epsilon=eps),
            p0,
            grads,
        )
        # Independent simulator: centered RMSProp with momentum.
        ga = np.zeros(5, np.float64)
        gsa = np.zeros(5, np.float64)
        upd = np.zeros(5, np.float64)
        ref = p0.astype(np.float64)
        for g in grads:
            ga = decay * ga + (1 - decay) * g
            gsa = decay * gsa + (1 - decay) * g * g
            rms = np.sqrt(gsa - ga * ga + eps)
            upd = momentum * upd - lr * g / rms
            ref = ref + upd
        np.testing.assert_allclose(p, ref, rtol=1e-4)


class TestAdam:
    def test_single_mode_matches_numpy(self, p0, grads):
        lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8
        p, state = rollout(
            rules.make("adam", lr=lr, beta1=b1, beta2=b2, epsilon=eps), p0, grads
        )
        m = np.zeros(5, np.float64)
        v = np.zeros(5, np.float64)
        ref = p0.astype(np.float64)
        for t, g in enumerate(grads, start=1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            lr_t = lr * np.sqrt(1 - b2**t) / (1 - b1**t)
            ref = ref - lr_t * m / (np.sqrt(v) + eps)
        np.testing.assert_allclose(p, ref, rtol=1e-4)
        assert int(state["t"]) == len(grads)

    def test_server_mode_step_div(self, p0, grads):
        """Server mode: bias-correction exponent floor(t/step_div)+1
        (reference BiCNN/pserver.lua:151-153)."""
        lr, b1, b2, eps, sd = 1e-3, 0.9, 0.999, 1e-8, 2
        p, _ = rollout(
            rules.make("adam", lr=lr, beta1=b1, beta2=b2, epsilon=eps, step_div=sd),
            p0,
            grads,
        )
        m = np.zeros(5, np.float64)
        v = np.zeros(5, np.float64)
        ref = p0.astype(np.float64)
        for t, g in enumerate(grads, start=1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            e = t // sd + 1
            lr_t = lr * np.sqrt(1 - b2**e) / (1 - b1**e)
            ref = ref - lr_t * m / (np.sqrt(v) + eps)
        np.testing.assert_allclose(p, ref, rtol=1e-4)


class TestAdamax:
    def test_matches_numpy(self, p0, grads):
        lr, b1, b2, eps = 2e-3, 0.9, 0.999, 1e-8
        p, _ = rollout(
            rules.make("adamax", lr=lr, beta1=b1, beta2=b2, epsilon=eps), p0, grads
        )
        m = np.zeros(5, np.float64)
        u = np.zeros(5, np.float64)
        ref = p0.astype(np.float64)
        for t, g in enumerate(grads, start=1):
            m = b1 * m + (1 - b1) * g
            u = np.maximum(b2 * u, np.abs(g) + eps)  # eps inside the max
            ref = ref - (lr / (1 - b1**t)) * m / u
        np.testing.assert_allclose(p, ref, rtol=1e-4)


class TestAdagrad:
    def test_matches_numpy(self, p0, grads):
        lr, lrd, eps = 1e-2, 0.1, 1e-10
        p, _ = rollout(rules.make("adagrad", lr=lr, lrd=lrd, epsilon=eps), p0, grads)
        var = np.zeros(5, np.float64)
        ref = p0.astype(np.float64)
        for k, g in enumerate(grads):
            clr = lr / (1 + k * lrd)
            var = var + g * g
            ref = ref - clr * g / (np.sqrt(var) + eps)
        np.testing.assert_allclose(p, ref, rtol=1e-4)


class TestAdadelta:
    def test_matches_numpy(self, p0, grads):
        lr, rho, eps = 1.0, 0.9, 1e-6
        p, _ = rollout(rules.make("adadelta", lr=lr, rho=rho, epsilon=eps), p0, grads)
        var = np.zeros(5, np.float64)
        acc = np.zeros(5, np.float64)
        ref = p0.astype(np.float64)
        for g in grads:
            var = rho * var + (1 - rho) * g * g
            delta = np.sqrt(acc + eps) / np.sqrt(var + eps) * g
            ref = ref - lr * delta
            acc = rho * acc + (1 - rho) * delta * delta
        np.testing.assert_allclose(p, ref, rtol=1e-4)


class TestRegistry:
    def test_names(self):
        assert set(rules.names()) == {
            "add",
            "rmsprop",
            "adam",
            "adamax",
            "adagrad",
            "adadelta",
        }

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            rules.make("nope")

    def test_state_slots_pins_real_init_shapes(self):
        # STATE_SLOTS is the footprint model's load-bearing constant
        # (bytes per server = (1 + slots) * 4 * elems): pin it against
        # what each rule's init ACTUALLY allocates per element.
        p = jnp.zeros(7, jnp.float32)
        for name in rules.names():
            state = rules.make(name).init(p)
            vector_arrays = sum(
                1 for v in state.values() if np.ndim(v) == 1)
            assert vector_arrays == rules.state_slots(name), name
            # anything that is not per-element must be a free scalar
            assert all(np.ndim(v) in (0, 1) for v in state.values()), name

    def test_state_slots_unknown_raises(self):
        with pytest.raises(ValueError):
            rules.state_slots("nope")


def quadratic_vgf(w, target):
    """loss = 0.5*||w-target||², grad = w-target."""
    loss = 0.5 * jnp.sum((w - target) ** 2)
    return loss, w - target


class TestMSGD:
    def test_no_momentum_is_plain_sgd(self, p0):
        cfg = MSGDConfig(lr=0.1)
        target = jnp.zeros(5)
        w = jnp.asarray(p0)
        state = msgd_init(w)
        w, state, _ = msgd_step(quadratic_vgf, w, state, cfg, target)
        np.testing.assert_allclose(np.asarray(w), p0 - 0.1 * p0, rtol=RTOL)

    def test_full_semantics_vs_numpy(self, p0):
        """Lookahead ordering + momentum ramp + lr decay + l2wd, 5 steps."""
        cfg = MSGDConfig(
            lr=0.1, lrd=0.01, lrp=2.0, mom=0.9, mommax=0.95, momdecay=10.0, l2wd=1e-3
        )
        target = np.zeros(5, np.float32)
        w = jnp.asarray(p0)
        state = msgd_init(w)
        step = jax.jit(
            lambda w, s, t: msgd_step(quadratic_vgf, w, s, cfg, t)
        )
        for _ in range(5):
            w, state, _ = step(w, state, jnp.asarray(target))

        # Independent reference-order simulator (optim-msgd.lua:20-40).
        ref = p0.astype(np.float64)
        vt = np.zeros(5, np.float64)
        for k in range(5):
            mom = min(cfg.mommax, 1 - 0.5 / (1 + k / cfg.momdecay))
            vt = mom * vt
            ref = ref + vt
            g = (ref - target) + cfg.l2wd * ref
            clr = cfg.lr / (1 + k * cfg.lrd) ** cfg.lrp
            ref = ref - clr * g
            vt = vt - clr * g
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)

    def test_momentum_ramp_capped(self):
        from mpit_tpu.optim.msgd import _effective_momentum

        cfg = MSGDConfig(mom=0.5, mommax=0.7, momdecay=1.0)
        m = _effective_momentum(cfg, jnp.asarray(10**6, jnp.int32))
        assert float(m) == pytest.approx(0.7)
