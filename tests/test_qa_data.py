"""QA data pipeline: parsing, vocab/OOV, padding, binary cache, synthetic."""

import numpy as np
import pytest

from mpit_tpu.data import qa


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("qa")
    paths = qa.synthetic_qa(d, n_labels=10, n_train=40, n_eval=12,
                            embedding_dim=6, vocab_words=50, seed=3)
    return paths


@pytest.fixture(scope="module")
def data(corpus):
    return qa.load_qa_files(embedding_dim=6, conv_width=2, **corpus)


class TestParsing:
    def test_reserved_tokens(self, data):
        assert data.vocab.str2idx["SENTBEGIN"] == qa.SENTBEGIN
        assert data.vocab.str2idx["SENTEND"] == qa.SENTEND
        # zero vectors for the sentinels (prepareData.lua:33-39)
        assert not data.vocab.vectors[0].any()
        assert not data.vocab.vectors[1].any()

    def test_counts(self, data):
        assert len(data.train) == 40
        assert len(data.valid) == len(data.test1) == len(data.test2) == 12
        assert data.answer_space == 10

    def test_sentence_padding(self, data):
        """conv_width SENTBEGINs then words then conv_width-1 SENTENDs
        (prepareData.lua:90-102)."""
        w = 2
        for i in range(len(data.train)):
            length = data.train.q_len[i]
            row = data.train.q_tokens[i]
            assert (row[:w] == qa.SENTBEGIN).all()
            # with conv_width=2 the final valid token is one SENTEND
            assert row[length - 1] == qa.SENTEND
            assert (row[w : length - (w - 1)] > qa.SENTEND).all()

    def test_oov_words_added(self, data):
        # synthetic embeddings cover 3/4 of the vocab + topic words are OOV
        n_pretrained = 50 * 3 // 4
        assert len(data.vocab) > n_pretrained + 2

    def test_oov_deterministic(self, corpus):
        a = qa.load_qa_files(embedding_dim=6, conv_width=2, oov_seed=5, **corpus)
        b = qa.load_qa_files(embedding_dim=6, conv_width=2, oov_seed=5, **corpus)
        np.testing.assert_array_equal(a.vocab.matrix(), b.vocab.matrix())

    def test_pools_reference_known_labels(self, data):
        l2r = data.label2row
        for pool in data.valid.pools:
            assert all(v in l2r for v in pool)

    def test_gold_label_in_pool(self, data):
        for labels, pool in zip(data.valid.labels, data.valid.pools):
            assert any(l in pool for l in labels)


class TestPackSequences:
    def test_pads_with_sentend(self):
        tok, lengths = qa.pack_sequences([[0, 5, 3], [0, 7]])
        assert tok.shape == (2, 3)
        np.testing.assert_array_equal(lengths, [3, 2])
        assert tok[1, 2] == qa.SENTEND

    def test_min_width(self):
        tok, _ = qa.pack_sequences([[4]], max_len=8)
        assert tok.shape == (1, 8)


class TestBinaryCache:
    def test_roundtrip(self, data, tmp_path):
        p = qa.save_binary(data, tmp_path / "cache.npz")
        back = qa.load_binary(p)
        np.testing.assert_array_equal(back.train.q_tokens, data.train.q_tokens)
        np.testing.assert_array_equal(back.answer_tokens, data.answer_tokens)
        np.testing.assert_array_equal(back.vocab.matrix(), data.vocab.matrix())
        assert back.train.labels == data.train.labels
        assert back.valid.pools == data.valid.pools
        assert back.answer_labels == data.answer_labels
        assert back.vocab.str2idx == data.vocab.str2idx

    def test_load_qa_prefers_binary(self, data, tmp_path):
        p = qa.save_binary(data, tmp_path / "cache.npz")
        got = qa.load_qa(binary_path=p)
        assert got.source.startswith("binary")
        assert len(got.train) == len(data.train)

    def test_cache_rejects_config_mismatch(self, data, tmp_path):
        p = qa.save_binary(data, tmp_path / "cache.npz")
        with pytest.raises(ValueError, match="conv_width"):
            qa.load_qa(binary_path=p, conv_width=data.conv_width + 1)
        with pytest.raises(ValueError, match="embedding_dim"):
            qa.load_qa(binary_path=p,
                       embedding_dim=data.vocab.embedding_dim + 1)
        # matching expectations load fine
        got = qa.load_qa(binary_path=p, conv_width=data.conv_width,
                         embedding_dim=data.vocab.embedding_dim)
        assert got.conv_width == data.conv_width


class TestSyntheticFallback:
    def test_load_qa_synthetic(self, tmp_path):
        got = qa.load_qa(embedding_dim=6, conv_width=3, synthetic_dir=tmp_path,
                         n_labels=8, n_train=20, n_eval=6, vocab_words=40)
        assert got.source.startswith("synthetic")
        assert len(got.train) == 20
        # conv_width respected in the padding
        assert (got.train.q_tokens[0][:3] == qa.SENTBEGIN).all()

    def test_regeneration_is_deterministic(self, tmp_path):
        a = qa.load_qa(embedding_dim=6, synthetic_dir=tmp_path / "a")
        b = qa.load_qa(embedding_dim=6, synthetic_dir=tmp_path / "b")
        np.testing.assert_array_equal(a.train.q_tokens, b.train.q_tokens)
        np.testing.assert_array_equal(a.vocab.matrix(), b.vocab.matrix())


class TestDocqaFixture:
    """The committed REAL corpus (stdlib docstrings, tools/make_docqa.py)."""

    def test_loads_through_reference_parser(self):
        paths = qa.docqa_paths()
        assert paths is not None, "fixture missing from checkout"
        data = qa.load_qa(embedding_dim=qa.DOCQA_EMBEDDING_DIM,
                          conv_width=2, paths=paths)
        assert len(data.train) > 900
        assert len(data.test1) > 100
        # 20-way candidate pools, gold label present in every pool
        for labs, pool in zip(data.test1.labels, data.test1.pools):
            assert len(pool) == 20
            assert any(l in pool for l in labs)
        # real text made it through: a known docstring word is in-vocab
        assert "string" in data.vocab.str2idx

    def test_builder_is_deterministic(self, tmp_path):
        """tools/make_docqa.py regenerates the committed fixture
        byte-for-byte (provenance guard).  Runs the script in a CLEAN
        interpreter: the harvest walks ``dir(module)``, and a host
        process's prior imports (pytest plugins instrumenting stdlib
        modules) can add attributes that change the corpus.  Skipped on
        a different CPython than the recorded builder — the corpus IS
        stdlib docstrings, which move between versions."""
        import json
        import pathlib
        import platform
        import subprocess
        import sys

        committed_dir = (pathlib.Path(__file__).parents[1]
                         / "data/fixtures/docqa")
        prov = json.loads((committed_dir / "PROVENANCE.json").read_text())
        if prov["python"] != platform.python_version():
            pytest.skip(
                f"fixture built on CPython {prov['python']}, running "
                f"{platform.python_version()} — stdlib docstrings differ"
            )
        script = (pathlib.Path(__file__).parents[1]
                  / "tools" / "make_docqa.py")
        subprocess.run([sys.executable, str(script), str(tmp_path)],
                       check=True, capture_output=True, timeout=300)
        committed = pathlib.Path(__file__).parents[1] / "data/fixtures/docqa"
        for name in ("train.tsv", "valid.tsv", "test1.tsv", "test2.tsv",
                     "label2answers.tsv", "embeddings.txt"):
            assert (tmp_path / name).read_bytes() == \
                (committed / name).read_bytes(), f"{name} diverged"
