"""Serving tier (docs/PROTOCOL.md §8) + event-loop transport scale-out:
READ-ONLY attach, the N-readers=1-copy invariant, BUSY admission control
with retry hints honored through the backoff loop, and the O(1)-threads /
no-fd-leak properties of the epoll event-loop TcpTransport."""

import os
import socket
import threading
import time

import numpy as np
import pytest

from mpit_tpu.comm.tcp import TcpTransport, allocate_local_addresses
from mpit_tpu.ft import FLAG_FRAMED, FTConfig, init_v3
from mpit_tpu.ps import ParamClient, ParamServer, ReaderClient, ServeConfig


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def _serve_gang(nservers, nreaders, *, serve_cfg, server_wrap=None,
                reader_ft=None):
    """Build a servers+writer TCP core (full mesh among them, lazy
    accepts for the rest) and return (addrs, nranks, sranks, wrank,
    reader_ranks, transports, servers, server_threads)."""
    nw = 1
    core = nservers + nw
    nranks = core + nreaders
    addrs, socks = allocate_local_addresses(core)
    addrs = addrs + ["127.0.0.1:0"] * nreaders  # readers never listen
    sranks = list(range(nservers))
    wrank = nservers
    readers = list(range(core, nranks))
    tr = {}

    def build(r):
        tr[r] = TcpTransport(r, nranks, addrs, listener=socks[r],
                             reconnect=30.0, dial_peers=list(range(r)))

    ths = [threading.Thread(target=build, args=(r,)) for r in range(core)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    assert all(r in tr for r in range(core)), "core mesh construction hung"
    servers = []
    for r in sranks:
        ep = tr[r] if server_wrap is None else server_wrap(r, tr[r])
        servers.append(ParamServer(r, [wrank], ep, rule="add",
                                   reader_ranks=readers, serve=serve_cfg))
    sth = [threading.Thread(target=s.start, daemon=True) for s in servers]
    for t in sth:
        t.start()
    return addrs, nranks, sranks, wrank, readers, tr, servers, sth


def _run_reader(rank, nranks, addrs, sranks, size, rounds, results,
                ft=None):
    t = TcpTransport(rank, nranks, addrs, reconnect=30.0,
                     dial_peers=sranks, listen=False)
    rc = ReaderClient(rank, sranks, t,
                      ft=ft or FTConfig(op_deadline_s=30.0))
    mirror = np.zeros(size, np.float32)
    rc.start(mirror)
    for _ in range(rounds):
        rc.read_params()
    results[rank] = {
        "mirror": mirror.copy(),
        "versions": dict(rc.versions),
        "monotone": rc.monotone,
        "busy_honored": rc.busy_honored,
    }
    rc.stop()
    t.close()


class TestReaderTier:
    def test_readers_share_one_snapshot_copy_per_version(self):
        """N readers x R reads of one committed version cost the server
        exactly one d2h copy + one encode (the PR 2 invariant pushed to
        the serving tier), observe a monotone version, and decode the
        exact seeded bytes."""
        size = 4096
        _addrs, nranks, sranks, wrank, readers, tr, servers, sth = \
            _serve_gang(2, 4, serve_cfg=ServeConfig(budget_bytes=1 << 30))
        addrs = _addrs
        client = ParamClient(wrank, sranks, tr[wrank], seed_servers=True,
                             ft=FTConfig(op_deadline_s=30.0))
        param = np.arange(size, dtype=np.float32)
        grad = np.zeros(size, np.float32)
        client.start(param, grad)
        results = {}
        rth = [threading.Thread(
            target=_run_reader,
            args=(r, nranks, addrs, sranks, size, 3, results))
            for r in readers]
        for t in rth:
            t.start()
        for t in rth:
            t.join(60)
            assert not t.is_alive(), "reader hung"
        client.stop()
        for t in sth:
            t.join(30)
            assert not t.is_alive(), "server never stopped"
        for r in readers:
            rec = results[r]
            assert rec["monotone"]
            np.testing.assert_array_equal(rec["mirror"], param)
        for s in servers:
            # Seed = one committed version; 4 readers x 3 reads of it
            # must share one copy/encode.
            assert s.snapshot_copies == 1, s.snapshot_copies
            assert s.params_served >= 12
        for r in list(range(3)):
            tr[r].close()

    def test_admission_burst_gets_busy_and_converges(self):
        """A reader burst over a 1-read budget through a
        delayed-reply server: BUSY-with-hint is issued at least once,
        every reader honors it through the backoff loop, and the final
        mirrors are bitwise-identical to an unthrottled run's."""
        from mpit_tpu.ft import FaultPlan, FaultyTransport
        from mpit_tpu.ps import tags

        size = 2048
        param = np.arange(size, dtype=np.float32) * 0.5

        def run(cfg, wrap):
            addrs, nranks, sranks, wrank, readers, tr, servers, sth = \
                _serve_gang(1, 3, serve_cfg=cfg, server_wrap=wrap)
            client = ParamClient(wrank, sranks, tr[wrank],
                                 seed_servers=True,
                                 ft=FTConfig(op_deadline_s=30.0))
            client.start(param.copy(), np.zeros(size, np.float32))
            results = {}
            rth = [threading.Thread(
                target=_run_reader,
                args=(r, nranks, addrs, sranks, size, 4, results))
                for r in readers]
            for t in rth:
                t.start()
            for t in rth:
                t.join(120)
                assert not t.is_alive(), "throttled reader hung"
            client.stop()
            for t in sth:
                t.join(60)
                assert not t.is_alive(), "server never stopped"
            busy = servers[0].busy_replies
            for r in list(range(2)):
                tr[r].close()
            return results, busy

        # Throttled leg: replies crawl (delay injection) so grants stay
        # in flight and the 1-read budget rejects the burst.
        def slow(rank, ep):
            return FaultyTransport(ep, FaultPlan(
                delay_every=1, delay_polls=400,
                tags=frozenset({tags.PARAM})))

        throttled, busy = run(
            ServeConfig(budget_reads=1, budget_bytes=1 << 30,
                        hint_floor_us=2000), slow)
        assert busy >= 1, "burst over a 1-read budget never drew a BUSY"
        honored = sum(rec["busy_honored"] for rec in throttled.values())
        assert honored >= 1, "no reader honored a BUSY hint"
        # Unthrottled control: same gang, effectively infinite budget.
        control, busy0 = run(
            ServeConfig(budget_reads=0, budget_bytes=1 << 30), None)
        assert busy0 == 0
        for rec in throttled.values():
            assert rec["monotone"]
            np.testing.assert_array_equal(rec["mirror"], param)
        for t_rec, c_rec in zip(throttled.values(), control.values()):
            np.testing.assert_array_equal(t_rec["mirror"], c_rec["mirror"])

    def test_reader_posture_is_validated(self):
        server = ParamServer(0, [1], transport=None, reader_ranks=[2])
        # A reader rank announcing without FLAG_READONLY is refused.
        with pytest.raises(ValueError, match="FLAG_READONLY"):
            server._negotiate(2, init_v3(0, 16, 0, 0, FLAG_FRAMED).tobytes())
        # A writer rank announcing the read-only posture is refused too.
        from mpit_tpu.ft import FLAG_READONLY
        with pytest.raises(ValueError, match="reader_ranks"):
            server._negotiate(
                1, init_v3(0, 16, 0, 0,
                           FLAG_FRAMED | FLAG_READONLY).tobytes())
        # Readers require framing (status replies echo the identity).
        with pytest.raises(ValueError, match="FLAG_FRAMED"):
            server._negotiate(
                2, init_v3(0, 16, 0, 0, FLAG_READONLY).tobytes())

    def test_reader_requires_deadlines_and_roles_disjoint(self):
        with pytest.raises(ValueError, match="op_deadline_s"):
            ReaderClient(3, [0], transport=None, ft=FTConfig())
        with pytest.raises(ValueError, match="overlap"):
            ParamServer(0, [1, 2], transport=None, reader_ranks=[2])


@pytest.mark.slow
def test_launch_serve_mode_end_to_end():
    """`--serve_readers N` through the real process-gang launcher: the
    last N ranks run READ-ONLY readers against the training gang and
    report monotone versions."""
    from mpit_tpu.train.launch import LAUNCH_DEFAULTS, launch_processes

    cfg = LAUNCH_DEFAULTS.merged(
        np=5, serve_readers=2, opt="downpour", epochs=1, model="linear",
        side=8, batch=64, ft_op_deadline_s=60.0, serve_rounds=4,
        serve_interval_s=0.02, ring_mb=8,
    )
    results = launch_processes(cfg, timeout=600)
    for r in (3, 4):
        assert results[r]["role"] == "reader"
        assert results[r]["monotone"] is True
        assert results[r]["reads"] == 4
    assert results[1]["role"] == "worker"


class TestEventLoopScaleOut:
    def _mesh(self, n, reconnect=20.0):
        addrs, socks = allocate_local_addresses(n)
        out = [None] * n

        def build(r):
            out[r] = TcpTransport(r, n, addrs, listener=socks[r],
                                  reconnect=reconnect)

        threads = [threading.Thread(target=build, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(o is not None for o in out), "mesh construction hung"
        return out

    def test_thread_count_is_o1_in_peer_count(self):
        """The acceptance bar: one I/O thread per rank regardless of
        peer count — the event loop replaced the per-peer reader/writer
        pairs (which would be 32 threads per rank at this mesh size)."""
        mesh = self._mesh(17)
        try:
            for tr in mesh:
                alive = [t for t in tr._threads if t.is_alive()]
                assert len(alive) == 1, [t.name for t in alive]
                assert alive[0].name.startswith("_io_loop")
            loops = [t for t in threading.enumerate()
                     if t.name.startswith("_io_loop")]
            assert len(loops) == 17
        finally:
            for tr in mesh:
                tr.close()

    @pytest.mark.slow
    def test_torture_sever_redial_16_peers_no_fd_leak(self):
        """Interleaved sever/redial across 16 peers: the hub's event
        loop redials every torn link concurrently, traffic resumes in
        both directions with no loss, and /proc/self/fd stays flat —
        every replaced socket is actually closed."""
        mesh = self._mesh(17)
        hub = mesh[16]
        payload = np.arange(512, dtype=np.float32)
        try:
            def roundtrip(tag):
                handles = [hub.isend(payload, p, tag) for p in range(16)]
                for p in range(16):
                    out = np.zeros_like(payload)
                    deadline = time.monotonic() + 30
                    h = mesh[p].irecv(16, tag, out=out)
                    while not mesh[p].test(h):
                        assert time.monotonic() < deadline, "delivery hung"
                        time.sleep(0.001)
                    np.testing.assert_array_equal(out, payload)
                    mesh[p].send(np.full(4, p, np.float32), 16, tag)
                for p in range(16):
                    back = np.zeros(4, np.float32)
                    hub.recv(p, tag, out=back)
                    assert back[0] == p
                deadline = time.monotonic() + 30
                for h in handles:
                    while not hub.test(h):
                        assert time.monotonic() < deadline, "ack hung"
                        time.sleep(0.001)

            roundtrip(5)  # warm traffic on every link
            time.sleep(0.2)
            fd0 = _fd_count()
            for round_ in range(3):
                # Tear EVERY hub link at once (the worst interleave).
                for p in range(16):
                    try:
                        hub._peers[p].shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                roundtrip(10 + round_)  # resend/dedup over fresh sockets
            time.sleep(0.5)
            fd1 = _fd_count()
            assert abs(fd1 - fd0) <= 8, (fd0, fd1)
            # Still O(1) threads after 48 reconnects.
            alive = [t for t in hub._threads if t.is_alive()]
            assert len(alive) == 1
        finally:
            for tr in mesh:
                tr.close()

    def test_fd_hygiene_across_transport_lifecycle(self):
        """Open/close cycles leak nothing: sockets, selector, wakeup
        pipe all die with the transport."""
        base = _fd_count()
        for _ in range(3):
            mesh = self._mesh(4, reconnect=0.0)
            for tr in mesh:
                tr.close()
        time.sleep(0.2)
        assert abs(_fd_count() - base) <= 4, (base, _fd_count())
