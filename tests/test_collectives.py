"""Host collectives over the Transport contract — the reference's MPI
Allreduce/Bcast/Iallreduce surface (mpifuncs.c:83,:145,:1357;
test/testreduceall.lua) rebuilt over the framework's own transports.

Each rank runs on its own thread over in-process endpoints (np=5 covers
non-power-of-two tree/ring shapes); one leg repeats allreduce over real
TCP sockets for cross-transport parity.
"""

import threading

import numpy as np
import pytest

from mpit_tpu.comm import HostCollectives
from mpit_tpu.comm.local import LocalRouter

N = 5  # odd, >4: exercises uneven ring chunks and ragged binomial trees


def run_ranks(n, fn):
    """fn(collectives, rank) on one thread per rank; returns results."""
    router = LocalRouter(n)
    out = [None] * n
    errs = [None] * n

    def body(r):
        try:
            out[r] = fn(HostCollectives(router.endpoint(r)), r)
        except BaseException as e:  # surfaced below
            errs[r] = e

    threads = [threading.Thread(target=body, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "collective hung"
    for e in errs:
        if e is not None:
            raise e
    return out


class TestHostCollectives:
    @pytest.mark.parametrize("size", [7, 4096])  # small: tree; large: ring
    def test_allreduce_sum(self, rng, size):
        inputs = [rng.normal(size=size).astype(np.float32) for _ in range(N)]
        want = np.sum(np.stack(inputs), axis=0)

        def body(coll, r):
            arr = inputs[r].copy()
            coll.allreduce(arr)
            return arr

        for arr in run_ranks(N, body):
            np.testing.assert_allclose(arr, want, rtol=1e-4, atol=1e-5)

    def test_allreduce_max(self, rng):
        inputs = [rng.normal(size=300).astype(np.float32) for _ in range(N)]
        want = np.max(np.stack(inputs), axis=0)
        out = run_ranks(N, lambda c, r: c.allreduce(inputs[r].copy(), op="max"))
        for arr in out:
            np.testing.assert_array_equal(arr, want)

    @pytest.mark.parametrize("root", [0, 3])
    def test_bcast(self, rng, root):
        seed = rng.normal(size=513).astype(np.float32)

        def body(coll, r):
            arr = seed.copy() if r == root else np.zeros(513, np.float32)
            return coll.bcast(arr, root=root)

        for arr in run_ranks(N, body):
            np.testing.assert_array_equal(arr, seed)

    def test_reduce_to_root(self, rng):
        inputs = [rng.normal(size=64).astype(np.float32) for _ in range(N)]
        want = np.sum(np.stack(inputs), axis=0)
        out = run_ranks(N, lambda c, r: (c.reduce(inputs[r].copy()), r)[0])
        np.testing.assert_allclose(out[0], want, rtol=1e-4, atol=1e-5)

    def test_barrier_synchronizes(self):
        """Every rank's pre-barrier write is visible to every rank after
        the barrier, across repeated rounds."""
        arrived = [np.zeros(N, bool) for _ in range(3)]

        def body(coll, r):
            for k in range(3):
                arrived[k][r] = True
                coll.barrier()
                assert arrived[k].all(), f"round {k}: barrier exited early"
            return True

        run_ranks(N, body)

    def test_iallreduce_test_wait(self, rng):
        """Iallreduce analog: test() may poll False mid-flight, wait()
        completes, results match (testireduceall.lua:32-39 shape)."""
        inputs = [rng.normal(size=2048).astype(np.float32) for _ in range(N)]
        want = np.sum(np.stack(inputs), axis=0)

        def body(coll, r):
            arr = inputs[r].copy()
            h = coll.allreduce_async(arr)
            h.test()  # legal mid-flight
            h.wait(60)
            assert h.test() is True
            return arr

        for arr in run_ranks(N, body):
            np.testing.assert_allclose(arr, want, rtol=1e-4, atol=1e-5)

    def test_back_to_back_no_crosstalk(self, rng):
        """Consecutive collectives use fresh tag rounds: a sum right
        after a max must not mix messages."""

        def body(coll, r):
            a = np.full(100, float(r), np.float32)
            b = np.full(100, float(r), np.float32)
            coll.allreduce(a, op="max")
            coll.allreduce(b, op="sum")
            return a[0], b[0]

        for mx, sm in run_ranks(N, body):
            assert mx == N - 1 and sm == sum(range(N))

    @pytest.mark.parametrize("block", [3, 512])
    def test_allgather(self, rng, block):
        inputs = [rng.normal(size=block).astype(np.float32) for _ in range(N)]
        want = np.concatenate(inputs)

        def body(coll, r):
            recv = np.empty(N * block, np.float32)
            coll.allgather(inputs[r].copy(), recv)
            return recv

        for recv in run_ranks(N, body):
            np.testing.assert_allclose(recv, want)

    @pytest.mark.parametrize("block", [3, 512])
    def test_reduce_scatter(self, rng, block):
        inputs = [rng.normal(size=N * block).astype(np.float32)
                  for _ in range(N)]
        want = np.sum(np.stack(inputs), axis=0)

        def body(coll, r):
            out = np.empty(block, np.float32)
            coll.reduce_scatter(inputs[r].copy(), out)
            return out

        for r, out in enumerate(run_ranks(N, body)):
            np.testing.assert_allclose(
                out, want[r * block:(r + 1) * block], rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("root", [0, 2])
    def test_scatter_gather_roundtrip(self, rng, root):
        src = rng.normal(size=N * 16).astype(np.float32)

        def body(coll, r):
            out = np.empty(16, np.float32)
            coll.scatter(src.copy() if r == root else None, out, root=root)
            back = (np.empty(N * 16, np.float32) if r == root else None)
            coll.gather(out * 2.0, back, root=root)
            return out, back

        results = run_ranks(N, body)
        for r, (out, _) in enumerate(results):
            np.testing.assert_allclose(out, src[r * 16:(r + 1) * 16])
        np.testing.assert_allclose(results[root][1], src * 2.0)

    def test_scan_inclusive_prefix(self, rng):
        inputs = [rng.normal(size=64).astype(np.float32) for _ in range(N)]

        def body(coll, r):
            arr = inputs[r].copy()
            coll.scan(arr)
            return arr

        for r, arr in enumerate(run_ranks(N, body)):
            want = np.sum(np.stack(inputs[: r + 1]), axis=0)
            np.testing.assert_allclose(arr, want, rtol=1e-4, atol=1e-5)

    def test_block_size_validation(self):
        router = LocalRouter(1)
        coll = HostCollectives(router.endpoint(0))
        with pytest.raises(ValueError, match="n\\*send"):
            coll.allgather(np.zeros(4, np.float32), np.zeros(5, np.float32))
        with pytest.raises(ValueError, match="n\\*out"):
            coll.reduce_scatter(np.zeros(5, np.float32), np.zeros(4, np.float32))

    def test_rejects_noncontiguous(self):
        router = LocalRouter(1)
        coll = HostCollectives(router.endpoint(0))
        with pytest.raises(ValueError, match="contiguous"):
            coll.allreduce(np.zeros((4, 4), np.float32)[:, ::2])

    def test_allreduce_over_tcp(self, rng):
        """Cross-transport parity: the same ring over real sockets."""
        from tests.test_tcp_transport import make_mesh_transports

        n = 4
        transports = make_mesh_transports(n)
        inputs = [rng.normal(size=1024).astype(np.float32) for _ in range(n)]
        want = np.sum(np.stack(inputs), axis=0)
        out = [None] * n

        def body(r):
            arr = inputs[r].copy()
            HostCollectives(transports[r]).allreduce(arr)
            out[r] = arr

        threads = [threading.Thread(target=body, args=(r,)) for r in range(n)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
                assert not t.is_alive()
        finally:
            for tr in transports:
                tr.close()
        for arr in out:
            np.testing.assert_allclose(arr, want, rtol=1e-4, atol=1e-5)
