"""The flag-lattice negotiation matrix (ISSUE 15): every one of the
2^7 client flag sets × INIT v1–v5, against every server posture config,
checked against the wire-schema registry's negotiation oracle
(mpit_tpu.analysis.schema.negotiate) — and one real wire op round-tripped
for every combination the lattice declares legal.

Two layers:

- ``TestNegotiationMatrix`` drives ``ParamServer._negotiate`` directly
  (transport=None) for every (version, flags, posture) cell and asserts
  accept/refuse AND the negotiated per-pair posture equal the oracle's
  verdict.  The schema registry and the server cannot quietly diverge:
  a new flag bit, requires edge, or negotiate-off rule lands in
  analysis/schema.py first or this matrix fails.
- ``TestLegalRoundTrips`` runs every oracle-accepted combination through
  a real in-process gang with a hand-rolled wire driver whose frame
  layouts are *derived from the oracle's effective posture* (ft/wire
  helpers) — announce, seed/push one op, read it back bitwise, stop.
  If the server's wire for a legal combo disagrees with the schema's
  predicted layout, the driver mis-frames and the leg fails loudly
  (deadline-bounded, never a hang).
"""

import threading

import numpy as np
import pytest

import mpit_tpu.ft.wire as ftw
from mpit_tpu.analysis import schema
from mpit_tpu.cells import wire as cellwire
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ps import ParamServer, tags
from mpit_tpu.shardctl import wire as scwire
from mpit_tpu.shardctl.shardmap import ShardMap

SIZE = 1024  # one codec block => single-chunk streams under FLAG_CHUNKED
CHUNK_ELEMS = 1024

#: (name, ParamServer kwargs, oracle kwargs) — the announcing rank is 1.
CONFIGS = [
    ("plain", {}, {}),
    ("reader", {"reader_ranks": [1]}, {"reader_rank": True,
                                       "serves_readers": True}),
    ("cell", {"cell_ranks": [1]}, {"cell_rank": True,
                                   "serves_cells": True}),
]


def _announce_bytes(version: int, flags: int) -> bytes:
    if version == 1:
        return np.asarray([0, SIZE], np.int64).tobytes()
    if version == 2:
        return np.asarray([0, SIZE, 0], np.int64).tobytes()
    if version == 3:
        return ftw.init_v3(0, SIZE, 0, 0, flags).tobytes()
    if version == 5:
        return ftw.init_v5(0, SIZE, 0, 0, flags, CHUNK_ELEMS).tobytes()
    if version == 4:
        return scwire.init_v4(0, 0, flags,
                              ShardMap.initial(SIZE, [0])).tobytes()
    raise AssertionError(version)


def _fresh_server(server_kw, transport=None):
    # client_ranks=[2] keeps rank 1 free for the reader/cell postures.
    return ParamServer(0, [2], transport, rule="add", **server_kw)


class TestNegotiationMatrix:
    """All 2^7 flag sets × v1–v5 × 3 server postures: the real
    ``_negotiate`` must agree with the schema oracle cell for cell —
    refusals loud, acceptances with the exact effective posture."""

    @pytest.mark.parametrize("config", CONFIGS, ids=lambda c: c[0])
    @pytest.mark.parametrize("version", [1, 2, 3, 4, 5])
    def test_matrix_matches_oracle(self, config, version):
        name, server_kw, oracle_kw = config
        flag_sets = range(128) if version in (3, 4, 5) else [0]
        mismatches = []
        for flags in flag_sets:
            want = schema.negotiate(version, flags, **oracle_kw)
            server = _fresh_server(server_kw)
            try:
                server._negotiate(1, _announce_bytes(version, flags))
                accepted = True
            except (ValueError, AssertionError):
                accepted = False
            ctx = f"{name} v{version} flags={flags:#04x}"
            if accepted != want.accepted:
                mismatches.append(
                    f"{ctx}: server {'accepted' if accepted else 'refused'}"
                    f" but the schema says "
                    f"{'accept' if want.accepted else 'refuse'}"
                    + (f" ({want.reason})" if want.reason else ""))
                continue
            if not accepted:
                continue
            got = {
                "framed": server._framed.get(1, False),
                "heartbeat": server._hb.get(1, False),
                "staleness": server._stale_track.get(1, False),
                "timing": server._timing.get(1, False),
                "readonly": server._readonly.get(1, False),
                "subscribe": server._subscribe.get(1, False),
                "chunked": bool(server._chunk.get(1, 0)),
                "shardctl": server._sc,
            }
            exp = {k: bool(getattr(want, k)) for k in got}
            if got != exp:
                diff = {k: (exp[k], got[k]) for k in got
                        if got[k] != exp[k]}
                mismatches.append(f"{ctx}: posture drift "
                                  f"(schema, server) = {diff}")
        assert not mismatches, "\n".join(mismatches)

    def test_matrix_has_both_verdicts(self):
        """Sanity on the oracle itself: the v3 space must contain both
        legal and refused cells for every posture config."""
        for name, _, oracle_kw in CONFIGS:
            verdicts = {schema.negotiate(3, f, **oracle_kw).accepted
                        for f in range(128)}
            assert verdicts == {True, False}, name


# ---------------------------------------------------------------------------
# Round trips — one real op per legal combination
# ---------------------------------------------------------------------------


def _legal(version, **oracle_kw):
    flag_sets = range(128) if version in (3, 4, 5) else [0]
    return [f for f in flag_sets
            if schema.negotiate(version, f, **oracle_kw).accepted]


def _recv(wire, src, tag, deadline_s=30.0):
    """Bounded blocking receive returning the raw payload bytes —
    a mis-framed leg fails the test instead of hanging it."""
    import time

    t0 = time.monotonic()
    while not wire.iprobe(src, tag):
        assert time.monotonic() - t0 < deadline_s, \
            f"no message from {src} on tag {tag} within {deadline_s}s"
        time.sleep(0.0005)
    return bytes(wire.recv(src, tag))


def _push_and_read(wire, out: "schema.Outcome", w0: np.ndarray) -> None:
    """Seed-push w0 then read it back, framing every message exactly as
    the oracle's effective posture dictates."""
    body = w0.view(np.uint8)
    if out.chunked:
        chdr = ftw.chunk_hdr_bytes(out.timing)
        stride = ftw.chunk_stride(chdr, body.size)
        frame = np.zeros(stride, np.uint8)
        ftw.pack_chunk_header(frame, 0, 1, 0, 1)
        if out.timing:
            ftw.pack_tx_stamp(frame, chdr, 1)
        frame[chdr:chdr + body.size] = body
        wire.send(frame, 0, tags.PARAM_PUSH)
        ack = np.frombuffer(_recv(wire, 0, tags.PARAM_PUSH_ACK), np.int64)
        assert ack.size == (ftw.CHUNK_ACK_TIMING_WORDS if out.timing
                            else ftw.CHUNK_ACK_WORDS)
        assert (int(ack[0]), int(ack[1]), int(ack[2])) == (0, 1, 0)
    elif out.shardctl:
        frame = np.zeros(scwire.SC_HDR_BYTES + body.size, np.uint8)
        scwire.pack_sc_header(frame, 0, 1, 0, 0)
        frame[scwire.SC_HDR_BYTES:] = body
        wire.send(frame, 0, tags.PARAM_PUSH)
        ep, seq, status, sid, _ = scwire.parse_reply(
            _recv(wire, 0, tags.PARAM_PUSH_ACK))
        assert (ep, seq, status, sid) == (0, 1, scwire.OK, 0)
    elif out.framed:
        hdr = ftw.hdr_bytes(out.staleness, out.timing)
        frame = np.zeros(hdr + body.size, np.uint8)
        ftw.pack_header(frame, 0, 1)
        if out.staleness:
            ftw.pack_version(frame, 0)
        if out.timing:
            ftw.pack_tx_stamp(frame, hdr, 1)
        frame[hdr:] = body
        wire.send(frame, 0, tags.PARAM_PUSH)
        ack = np.frombuffer(_recv(wire, 0, tags.PARAM_PUSH_ACK), np.int64)
        assert ack.size == (ftw.ACK_TIMING_WORDS if out.timing else 2)
        assert (int(ack[0]), int(ack[1])) == (0, 1)
    else:
        wire.send(w0, 0, tags.PARAM_PUSH)
        assert _recv(wire, 0, tags.PARAM_PUSH_ACK) == b""

    # -- read it back -----------------------------------------------------
    if out.chunked:
        req = (ftw.timed_frame(0, 2, 1) if out.timing
               else ftw.header_frame(0, 2))
        wire.send(req, 0, tags.PARAM_REQ)
        raw = _recv(wire, 0, tags.PARAM)
        chdr = ftw.chunk_reply_hdr_bytes(out.timing)
        words = np.frombuffer(raw[:8 * ftw.CHUNK_REPLY_WORDS], np.int64)
        assert (int(words[0]), int(words[1])) == (0, 2)
        assert (int(words[2]), int(words[3])) == (0, 1)  # chunk 0 of 1
        got = np.frombuffer(raw[chdr:chdr + w0.nbytes], np.float32)
    elif out.shardctl:
        wire.send(scwire.sc_header(0, 1, 0, 0), 0, tags.PARAM_REQ)
        ep, seq, status, sid, payload = scwire.parse_reply(
            _recv(wire, 0, tags.PARAM))
        assert (ep, seq, status, sid) == (0, 1, scwire.OK, 0)
        got = np.frombuffer(payload, np.float32)
    elif out.framed:
        req = (ftw.timed_frame(0, 2, 1) if out.timing
               else ftw.header_frame(0, 2))
        wire.send(req, 0, tags.PARAM_REQ)
        raw = _recv(wire, 0, tags.PARAM)
        hdr = ftw.reply_hdr_bytes(out.staleness, out.timing)
        echo = np.frombuffer(raw[:16], np.int64)
        assert (int(echo[0]), int(echo[1])) == (0, 2)
        got = np.frombuffer(raw[hdr:], np.float32)
    else:
        wire.send(tags.EMPTY, 0, tags.PARAM_REQ)
        got = np.frombuffer(_recv(wire, 0, tags.PARAM), np.float32)
    np.testing.assert_array_equal(got, w0)


def _run_server(server):
    t = threading.Thread(target=server.start, daemon=True)
    t.start()
    return t


def _join(server, t):
    t.join(30)
    alive = t.is_alive()
    if alive:
        server.live.stop()
        t.join(5)
    assert not alive, "server did not stop (stop-protocol hang)"


class TestLegalRoundTrips:
    """Every oracle-accepted (version, flags, posture) combination ships
    one real op over the in-process transport and reads it back
    bitwise."""

    @pytest.mark.parametrize("version", [1, 2, 3, 5, 4])
    def test_writer_combos(self, version):
        w0 = np.arange(SIZE, dtype=np.float32)
        for flags in _legal(version):
            out = schema.negotiate(version, flags)
            router = LocalRouter(2)
            server = ParamServer(0, [1], router.endpoint(0), rule="add")
            t = _run_server(server)
            try:
                wire = router.endpoint(1)
                wire.send(np.frombuffer(
                    _announce_bytes(version, flags), np.int64), 0,
                    tags.INIT)
                _push_and_read(wire, out, w0)
                wire.send(tags.EMPTY, 0, tags.STOP)
                _join(server, t)
            finally:
                server.live.stop()

    def test_reader_combos(self):
        """READ-ONLY legs: status-framed reads (§8) for every legal
        reader flag set."""
        w0 = np.arange(SIZE, dtype=np.float32)
        legal = _legal(3, reader_rank=True, serves_readers=True)
        assert len(legal) == 8, legal  # {RO,FRAMED} x {HB,STALE,TIMING}
        for flags in legal:
            router = LocalRouter(3)
            server = ParamServer(0, [2], router.endpoint(0), rule="add",
                                 reader_ranks=[1])
            t = _run_server(server)
            try:
                writer = router.endpoint(2)
                writer.send(np.asarray([0, SIZE], np.int64), 0, tags.INIT)
                writer.send(w0, 0, tags.PARAM_PUSH)
                _recv(writer, 0, tags.PARAM_PUSH_ACK)
                reader = router.endpoint(1)
                reader.send(ftw.init_v3(0, SIZE, 0, 0, flags), 0,
                            tags.INIT)
                reader.send(ftw.header_frame(0, 1), 0, tags.PARAM_REQ)
                status = np.frombuffer(_recv(reader, 0, tags.PARAM),
                                       np.int64)
                assert status.size == 4
                assert (int(status[0]), int(status[1])) == (0, 1)
                assert int(status[2]) == scwire.OK
                got = np.frombuffer(_recv(reader, 0, tags.PARAM),
                                    np.float32)
                np.testing.assert_array_equal(got, w0)
                reader.send(tags.EMPTY, 0, tags.STOP)
                writer.send(tags.EMPTY, 0, tags.STOP)
                _join(server, t)
            finally:
                server.live.stop()

    @pytest.mark.parametrize("version", [3, 5])
    def test_cell_combos(self, version):
        """SUBSCRIBE legs: the attach FULL frame of the diff stream
        (§11.2; chunk-framed under v5, §11.8) for every legal cell flag
        set."""
        w0 = np.arange(SIZE, dtype=np.float32)
        legal = _legal(version, cell_rank=True, serves_cells=True)
        assert len(legal) == 8, (version, legal)
        for flags in legal:
            out = schema.negotiate(version, flags, cell_rank=True,
                                   serves_cells=True)
            router = LocalRouter(3)
            server = ParamServer(0, [2], router.endpoint(0), rule="add",
                                 cell_ranks=[1])
            t = _run_server(server)
            try:
                writer = router.endpoint(2)
                writer.send(np.asarray([0, SIZE], np.int64), 0, tags.INIT)
                writer.send(w0, 0, tags.PARAM_PUSH)
                _recv(writer, 0, tags.PARAM_PUSH_ACK)
                cell = router.endpoint(1)
                cell.send(np.frombuffer(
                    _announce_bytes(version, flags), np.int64), 0,
                    tags.INIT)
                if out.chunked:
                    (kind, _f, _to, _head, idx, cnt,
                     body) = cellwire.parse_diff_chunk(
                        _recv(cell, 0, tags.DIFF))
                    assert (idx, cnt) == (0, 1)  # one block => one chunk
                else:
                    kind, _f, _to, _head, body = cellwire.parse_diff(
                        _recv(cell, 0, tags.DIFF))
                assert kind == cellwire.DIFF_FULL
                np.testing.assert_array_equal(
                    np.frombuffer(bytes(body), np.float32), w0)
                cell.send(tags.EMPTY, 0, tags.STOP)
                writer.send(tags.EMPTY, 0, tags.STOP)
                _join(server, t)
            finally:
                server.live.stop()
