"""BiCNN model family: layers, towers, GESD head, loss.

Math is checked against independent numpy derivations of the reference
formulas (BiCNN/bicnn.lua:98-105, Normalize.lua, DivideConstant.lua) —
not against the JAX code itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import BiCNN, BiCNNTower, gesd, margin_ranking_loss
from mpit_tpu.models.layers import divide_constant, lp_normalize, masked_max_pool

V, D, H, F, K = 30, 8, 10, 12, 2  # tiny tower dims


@pytest.fixture(scope="module")
def tower():
    m = BiCNNTower(vocab_size=V, embedding_dim=D, word_hidden_dim=H,
                   num_filters=F, conv_width=K)
    tok = jnp.zeros((1, 6), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), tok, jnp.array([6]))
    return m, params


class TestLayers:
    def test_lp_normalize_unit_norm(self, rng):
        x = jnp.asarray(rng.normal(size=(4, 7)).astype(np.float32))
        y = lp_normalize(x, p=2.0)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1), 1.0, rtol=1e-5
        )

    def test_lp_normalize_grad_matches_jacobian(self, rng):
        # The reference hand-derives this Jacobian (Normalize.lua:40-76):
        # d(x_i/n)/dx_j = delta_ij/n - x_i x_j / n^3.  Check autodiff
        # against that closed form.
        x = rng.normal(size=5).astype(np.float32)
        v = rng.normal(size=5).astype(np.float32)

        def f(x):
            return jnp.sum(lp_normalize(jnp.asarray(x), p=2.0) * v)

        g = np.asarray(jax.grad(f)(x))
        n = np.linalg.norm(x)
        want = v / n - x * (v @ x) / n**3
        np.testing.assert_allclose(g, want, rtol=1e-3, atol=1e-6)

    def test_divide_constant(self, rng):
        x = rng.uniform(1.0, 2.0, size=6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(divide_constant(jnp.asarray(x), 3.0)), 3.0 / x, rtol=1e-6
        )
        g = np.asarray(jax.grad(lambda x: jnp.sum(divide_constant(x, 3.0)))(jnp.asarray(x)))
        np.testing.assert_allclose(g, -3.0 / x**2, rtol=1e-5)  # DivideConstant.lua:19-25

    def test_masked_max_pool(self, rng):
        frames = rng.normal(size=(3, 5, 4)).astype(np.float32)
        n_valid = np.array([2, 5, 1])
        got = np.asarray(masked_max_pool(jnp.asarray(frames), jnp.asarray(n_valid)))
        for i, nv in enumerate(n_valid):
            np.testing.assert_allclose(got[i], frames[i, :nv].max(axis=0), rtol=1e-6)


class TestGesd:
    def test_matches_reference_formula(self, rng):
        u = rng.normal(size=(4, 6)).astype(np.float32)
        v = rng.normal(size=(4, 6)).astype(np.float32)
        got = np.asarray(gesd(jnp.asarray(u), jnp.asarray(v)))
        dot = (u * v).sum(-1)
        l2 = np.linalg.norm(u - v, axis=-1)
        want = 1.0 / ((1.0 + l2) * (1.0 + np.exp(-(dot + 1.0))))  # bicnn.lua:440-443
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_identical_vectors_score_highest(self, rng):
        u = np.asarray(lp_normalize(jnp.asarray(rng.normal(size=(1, 6)).astype(np.float32))))
        w = np.asarray(lp_normalize(jnp.asarray(rng.normal(size=(1, 6)).astype(np.float32))))
        same = float(gesd(jnp.asarray(u), jnp.asarray(u))[0])
        diff = float(gesd(jnp.asarray(u), jnp.asarray(w))[0])
        assert same > diff


class TestTower:
    def test_output_is_unit_normalized(self, tower, rng):
        m, params = tower
        tok = jnp.asarray(rng.integers(0, V, size=(5, 9)), jnp.int32)
        lengths = jnp.asarray([9, 4, 6, 2, 9])
        out = m.apply(params, tok, lengths)
        assert out.shape == (5, F)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1), 1.0, rtol=1e-4
        )

    def test_padding_invariance(self, tower, rng):
        """Tokens past `length` must not affect the embedding — the static
        -shape masking contract (models/layers.masked_max_pool)."""
        m, params = tower
        base = rng.integers(0, V, size=(1, 5)).astype(np.int32)
        a = np.concatenate([base, np.full((1, 4), 1, np.int32)], axis=1)
        b = np.concatenate([base, rng.integers(0, V, size=(1, 4)).astype(np.int32)], axis=1)
        ea = m.apply(params, jnp.asarray(a), jnp.asarray([5]))
        eb = m.apply(params, jnp.asarray(b), jnp.asarray([5]))
        np.testing.assert_allclose(np.asarray(ea), np.asarray(eb), rtol=1e-5)

    def test_length_changes_output(self, tower, rng):
        m, params = tower
        tok = jnp.asarray(rng.integers(2, V, size=(1, 8)), jnp.int32)
        e5 = np.asarray(m.apply(params, tok, jnp.asarray([5])))
        e8 = np.asarray(m.apply(params, tok, jnp.asarray([8])))
        assert not np.allclose(e5, e8)


class TestBiCNN:
    def test_weight_tying_by_construction(self, rng):
        """The same sentence through the Q and A paths gives the same
        embedding — the property the reference enforces with 40 lines of
        :set() aliasing (bicnn.lua:30-91)."""
        m = BiCNN(vocab_size=V, embedding_dim=D, word_hidden_dim=H,
                  num_filters=F, conv_width=K)
        tok = jnp.asarray(rng.integers(0, V, size=(2, 7)), jnp.int32)
        lengths = jnp.asarray([7, 5])
        params = m.init(jax.random.PRNGKey(1), tok, lengths, tok, lengths, tok, lengths)
        s_pos, s_neg = m.apply(params, tok, lengths, tok, lengths, tok, lengths)
        # identical a+ and a- inputs -> identical scores through tied towers
        np.testing.assert_allclose(np.asarray(s_pos), np.asarray(s_neg), rtol=1e-6)
        emb = m.apply(params, tok, lengths, method=BiCNN.embed)
        np.testing.assert_allclose(
            np.asarray(s_pos), np.asarray(gesd(emb, emb)), rtol=1e-6
        )

    def test_single_param_collection(self):
        """Tied towers must contribute ONE copy of each weight to the flat
        vector (getParameters dedupes aliases the same way)."""
        m = BiCNN(vocab_size=V, embedding_dim=D, word_hidden_dim=H,
                  num_filters=F, conv_width=K)
        tok = jnp.zeros((1, 6), jnp.int32)
        ln = jnp.asarray([6])
        params = m.init(jax.random.PRNGKey(0), tok, ln, tok, ln, tok, ln)
        leaves = jax.tree_util.tree_leaves(params)
        total = sum(x.size for x in leaves)
        expected = (
            V * D  # embedding
            + D * H + H  # word hidden
            + K * H * F + F  # temporal conv
        )
        assert total == expected


class TestMarginRankingLoss:
    def test_values(self):
        s_pos = jnp.asarray([0.9, 0.5, 0.2])
        s_neg = jnp.asarray([0.1, 0.49, 0.3])
        out = np.asarray(margin_ranking_loss(s_pos, s_neg, margin=0.02))
        np.testing.assert_allclose(out[0], 0.0, atol=1e-7)  # big gap: no loss
        np.testing.assert_allclose(out[1], 0.02 - 0.01, rtol=1e-5)
        np.testing.assert_allclose(out[2], 0.02 + 0.1, rtol=1e-5)
