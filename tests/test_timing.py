"""Tests for the latency-cancelled device timing helper."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.utils.timing import fetch_scalar, timed_per_call


def test_fetch_scalar_forces_value():
    x = jnp.arange(8.0)
    assert fetch_scalar(jax.jit(lambda a: a * 2)(x)) == 0.0
    assert fetch_scalar((jnp.float32(3.0), jnp.zeros(4))) == 3.0


def test_timed_per_call_positive_and_finite():
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((64, 64))
    t = timed_per_call(f, x, iters=3)
    assert np.isfinite(t) and t > 0


def test_timed_per_call_scales_with_work():
    # 8x the matmul work should cost measurably more per call; the only
    # claim tested is monotonicity with a wide margin, not absolute time.
    f = jax.jit(lambda a: a @ a)
    small = jnp.ones((128, 128))
    big = jnp.ones((1024, 1024))
    t_small = min(timed_per_call(f, small, iters=20) for _ in range(3))
    t_big = min(timed_per_call(f, big, iters=20) for _ in range(3))
    assert t_big > t_small


def test_timed_per_call_rejects_zero_division():
    # Degenerate fast fn must not return <= 0 (the max(..., eps) guard).
    f = jax.jit(lambda a: a)
    t = timed_per_call(f, jnp.zeros(1), iters=2)
    assert t > 0
