"""Tests for the latency-cancelled device timing helper."""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.utils.timing import fetch_scalar, timed_per_call


def test_fetch_scalar_forces_value():
    x = jnp.arange(8.0)
    assert fetch_scalar(jax.jit(lambda a: a * 2)(x)) == 0.0
    assert fetch_scalar((jnp.float32(3.0), jnp.zeros(4))) == 3.0


def test_timed_per_call_positive_and_finite():
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((64, 64))
    t = timed_per_call(f, x, iters=3)
    assert np.isfinite(t) and t > 0


def test_timed_per_call_scales_with_work():
    # 8x the matmul work should cost measurably more per call; the only
    # claim tested is monotonicity with a wide margin, not absolute time.
    f = jax.jit(lambda a: a @ a)
    small = jnp.ones((128, 128))
    big = jnp.ones((1024, 1024))
    t_small = min(timed_per_call(f, small, iters=20) for _ in range(3))
    t_big = min(timed_per_call(f, big, iters=20) for _ in range(3))
    assert t_big > t_small


def test_timed_per_call_rejects_zero_division():
    # Degenerate fast fn must not return <= 0 (the floor guard).
    f = jax.jit(lambda a: a)
    t = timed_per_call(f, jnp.zeros(1), iters=2)
    assert t > 0


def test_timed_per_call_auto_scale_stays_positive():
    """A sub-resolution op at iters=1 (the flake regime: differencing two
    loaded-host minima can go <=0) must auto-scale to a strictly positive
    estimate that survives millisecond rounding."""
    f = jax.jit(lambda a: a + 1)
    t = timed_per_call(f, jnp.zeros(1), iters=1, auto_scale=True,
                       max_iters=512)
    assert np.isfinite(t) and t > 0


def test_timed_per_call_auto_scale_grows_iters(monkeypatch):
    """When deltas hide inside jitter, iters must double until the delta
    clears it — simulated with a deterministic fake clock whose noise
    dwarfs the per-call cost at small iters."""
    from mpit_tpu.utils import timing as T

    calls = {"n": 0}
    per_call = 1e-6

    class FakeClock:
        """Seeded pseudo-random read noise (~5e-4 spread) dwarfing
        iters*per_call until iters reaches the many-hundreds."""

        def __init__(self):
            self.t = 0.0
            # seed 3: simulated beforehand to keep delta inside jitter
            # until iters reaches 512 (a lucky seed can clear the
            # statistical stop rule on round one — the floor, not the
            # escalation, is what guarantees positivity there)
            self.rng = np.random.default_rng(3)

        def __call__(self):
            return self.t + self.rng.uniform(0.0, 5e-4)

    clock = FakeClock()

    def fake_fn():
        calls["n"] += 1
        clock.t += per_call
        return np.zeros(1)

    # patch timing.py's module reference, not stdlib time: any other
    # perf_counter reader would otherwise consume FakeClock RNG draws
    # and break the pinned-seed determinism
    monkeypatch.setattr(
        T, "time", types.SimpleNamespace(perf_counter=clock))
    monkeypatch.setattr(T, "fetch_scalar", lambda out: 0.0)
    t = T.timed_per_call(fake_fn, iters=2, repeats=3, auto_scale=True,
                         max_iters=4096)
    # the loop must have escalated well past the starting 2 iters (a
    # non-escalating run makes 13 fn calls: 1 warmup + 3x1 small + 3x3 big)
    assert calls["n"] > 200
    assert t == pytest.approx(per_call, rel=2.0)
