"""Causal op tracing — clock estimator, FLAG_TIMING wire, joiner,
latency decomposition, critical path.

Three layers of assertion:

1. the clock estimator and wire-layout primitives (offset recovery on
   constructed exchanges, minimum-RTT filtering, header sizes);
2. deterministic joiner behavior on **synthetic** two-rank traces with
   a known injected clock skew: the recovered offset lands within the
   rtt/2 bound, every phase is non-negative, and the decomposition sums
   to the op's client wall time exactly;
3. round trips on **real** gangs (LocalRouter 2s/2c, FLAG_TIMING on):
   every completed framed op joins, the wire-level estimator state
   rides the trace, a drop plan's retry attempts appear as separate
   attempt chains matching the plan arithmetic, and legacy peers
   negotiate the extension off per pair.
"""

import json
import threading

import numpy as np
import pytest

from mpit_tpu import obs
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import (
    ACK_TIMING_WORDS,
    FLAG_FRAMED,
    FLAG_TIMING,
    FaultPlan,
    FaultyTransport,
    FTConfig,
    hdr_bytes,
    pack_reply_stamps,
    pack_tx_stamp,
    reply_hdr_bytes,
    unpack_reply_stamps,
    unpack_tx_stamp,
)
from mpit_tpu.obs import causal as obs_causal
from mpit_tpu.obs import clock as obs_clock
from mpit_tpu.obs import trace as obs_trace
from mpit_tpu.ps import ParamClient, ParamServer, tags

#: fast retry posture with the timing extension on (LocalRouter speed)
TIMED_FT = FTConfig(op_deadline_s=0.25, max_retries=8,
                    backoff_base_s=0.005, backoff_cap_s=0.02, timing=True)


@pytest.fixture
def obs_on():
    obs.configure(enabled=True, reset=True)
    try:
        yield obs.get_registry()
    finally:
        obs.configure(enabled=None, reset=True)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# clock estimator + wire primitives


class TestClockEstimator:
    def test_symmetric_exchange_recovers_offset_exactly(self):
        clock = obs_clock.PeerClock()
        # peer clock = local + 5000us; 100us each way, 30us turnaround
        t1 = 1_000_000
        assert clock.add(t1, t1 + 100 + 5000, t1 + 130 + 5000, t1 + 230)
        assert clock.offset_us == pytest.approx(5000.0)
        assert clock.uncertainty_us == pytest.approx(100.0)  # rtt/2

    def test_asymmetry_error_stays_within_rtt_bound(self):
        clock = obs_clock.PeerClock()
        skew, out, back = -7000, 20, 380  # pathological asymmetry
        t1 = 2_000_000
        clock.add(t1, t1 + out + skew, t1 + out + skew + 10,
                  t1 + out + 10 + back)
        assert abs(clock.offset_us - skew) <= clock.uncertainty_us

    def test_min_rtt_sample_wins(self):
        clock = obs_clock.PeerClock()
        t1 = 1_000_000
        clock.add(t1, t1 + 500, t1 + 510, t1 + 1010)          # rtt 1000
        assert clock.rtt_us == pytest.approx(1000.0)
        assert clock.add(t1 + 5000, t1 + 5100, t1 + 5110, t1 + 5210)
        assert clock.rtt_us == pytest.approx(200.0)           # better won
        # a worse later sample does not displace the best
        assert not clock.add(t1 + 9000, t1 + 9400, t1 + 9410, t1 + 9810)
        assert clock.rtt_us == pytest.approx(200.0)

    def test_garbage_exchange_rejected(self):
        clock = obs_clock.PeerClock()
        # negative rtt: echoed stamp from a different attempt
        assert not clock.add(2_000_000, 1_000_000, 3_000_000, 2_000_100)
        assert clock.samples == 1 and clock.accepted == 0

    def test_drift_aging_lets_fresh_samples_replace_stale_best(self):
        clock = obs_clock.PeerClock()
        t1 = 1_000_000
        clock.add(t1, t1 + 50, t1 + 60, t1 + 110)             # rtt 100
        # 10 s later, a 500us-rtt sample: aged best = 100 + 10*100ppm
        # = 1100us, so the fresh one wins despite the larger rtt.
        t2 = t1 + 10_000_000
        assert clock.add(t2, t2 + 250, t2 + 260, t2 + 510)
        assert clock.rtt_us == pytest.approx(500.0)

    def test_estimator_registry_snapshot(self):
        est = obs_clock.ClockEstimator()
        est.add_exchange(0, 1_000_000, 1_000_100, 1_000_110, 1_000_210)
        obs_clock.register("clienttest", est)
        snap = obs_clock.snapshot_all()
        assert "clienttest" in snap and "0" in snap["clienttest"]
        obs_clock.reset()
        assert "clienttest" not in obs_clock.snapshot_all()


class TestTimingWire:
    def test_header_sizes(self):
        assert hdr_bytes(False, False) == 16
        assert hdr_bytes(True, False) == 24
        assert hdr_bytes(False, True) == 24
        assert hdr_bytes(True, True) == 32
        assert reply_hdr_bytes(False, True) == 40
        assert reply_hdr_bytes(True, True) == 48
        assert ACK_TIMING_WORDS == 5
        assert FLAG_TIMING == 8 and not (FLAG_TIMING & (FLAG_FRAMED | 6))

    def test_tx_stamp_roundtrip_last_header_word(self):
        buf = np.zeros(64, np.uint8)
        for hdr in (24, 32):
            pack_tx_stamp(buf, hdr, 123456789)
            assert unpack_tx_stamp(buf, hdr) == 123456789
            # the stamp never touches [epoch, seq]
            assert buf[:16].view(np.int64).tolist() == [0, 0]

    def test_reply_stamps_roundtrip(self):
        buf = np.zeros(64, np.uint8)
        pack_reply_stamps(buf, 24, 1, 2, 3)
        assert unpack_reply_stamps(buf, 24) == (1, 2, 3)

    def test_timing_without_framing_is_inert(self):
        cfg = FTConfig(timing=True)
        assert not cfg.timing_track
        router = LocalRouter(2)
        client = ParamClient(1, [0], router.endpoint(1), ft=cfg)
        assert not client._timing and client._hdr == 0


# ---------------------------------------------------------------------------
# synthetic traces: known skew in, recovered offset + clean phases out


def synth_trace(skew_us: float, n_ops: int = 3, clock_meta=None) -> dict:
    """A two-rank trace: client rank 3 drives ``n_ops`` GRADs against
    server rank 0 whose clock runs ``skew_us`` ahead.  Wire is 50us
    out / 50us back, apply 300us, per-op spacing 10ms."""
    events = []
    for i in range(n_ops):
        c0 = 1_000_000.0 + i * 10_000
        send_done = c0 + 300
        s_recv = send_done + 50 + skew_us          # server clock
        s_ack = s_recv + 20 + 300                  # after queue + apply
        ack_done = s_ack - skew_us + 50            # client clock
        events += [
            {"ph": "B", "name": "GRAD", "cat": "ps_op", "pid": 3, "tid": 1,
             "ts": c0, "args": {"rank": 3, "peer": 0, "side": "client",
                                "epoch": 0, "seq": i + 1}},
            {"ph": "X", "name": "GRAD.encode", "cat": "ps_phase", "pid": 3,
             "tid": 1, "ts": c0, "dur": 100.0},
            {"ph": "X", "name": "GRAD.send", "cat": "ps_phase", "pid": 3,
             "tid": 1, "ts": c0 + 100, "dur": 200.0},
            {"ph": "X", "name": "GRAD.ack", "cat": "ps_phase", "pid": 3,
             "tid": 1, "ts": send_done, "dur": ack_done - send_done},
            {"ph": "E", "name": "GRAD", "cat": "ps_op", "pid": 3, "tid": 1,
             "ts": ack_done, "args": {"outcome": "ok"}},
            {"ph": "B", "name": "GRAD", "cat": "ps_op", "pid": 0, "tid": 1,
             "ts": s_recv, "args": {"rank": 0, "peer": 3, "side": "server",
                                    "epoch": 0, "seq": i + 1}},
            {"ph": "X", "name": "GRAD.apply", "cat": "ps_phase", "pid": 0,
             "tid": 1, "ts": s_recv + 20, "dur": 300.0},
            {"ph": "X", "name": "GRAD.ack", "cat": "ps_phase", "pid": 0,
             "tid": 1, "ts": s_ack, "dur": 10.0},
            {"ph": "E", "name": "GRAD", "cat": "ps_op", "pid": 0, "tid": 1,
             "ts": s_ack + 10, "args": {"outcome": "applied"}},
        ]
    events.sort(key=lambda e: e["ts"])
    other = {}
    if clock_meta is not None:
        other["clock"] = clock_meta
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


class TestSyntheticJoin:
    @pytest.mark.parametrize("skew_us", [0.0, 37_000.0, -250_000.0])
    def test_injected_skew_recovered_within_bound(self, skew_us):
        report = obs_causal.analyze(synth_trace(skew_us))
        assert report["ops"]["join_rate"] == 1.0
        assert report["violations"] == []
        (entry,) = report["offsets"]
        assert entry["source"] == "derived"
        # symmetric synthetic wire => the NTP estimate is exact up to
        # the turnaround; always within the rtt/2 bound
        assert abs(entry["offset_us"] - skew_us) <= entry["uncertainty_us"]
        assert abs(entry["offset_us"] - skew_us) <= 200.0

    def test_phases_nonnegative_and_sum_to_wall(self):
        report = obs_causal.analyze(synth_trace(37_000.0))
        for d in report["chains"]:
            assert d["joined"]
            for phase, value in d["phases"].items():
                assert value >= 0.0, (phase, value)
            assert sum(d["phases"].values()) == pytest.approx(
                d["wall_us"], abs=d["uncertainty_us"] + 1.0)

    def test_recorded_wire_offsets_preferred(self):
        meta = {"client3": {"0": {"offset_us": 37_000.0,
                                  "uncertainty_us": 25.0, "rtt_us": 50.0,
                                  "samples": 8, "accepted": 4}}}
        report = obs_causal.analyze(synth_trace(37_000.0, clock_meta=meta))
        (entry,) = report["offsets"]
        assert entry["source"] == "wire"
        assert entry["offset_us"] == 37_000.0
        assert report["violations"] == []

    def test_flow_events_pair_and_validate(self, tmp_path):
        path = tmp_path / "synth.json"
        path.write_text(json.dumps(synth_trace(1000.0, n_ops=2)))
        out = tmp_path / "flow.json"
        n = obs_causal.emit_flow(str(path), str(out))
        assert n == 2 * 2 * 2  # request + reply arrow per op, s+f each
        obj = json.loads(out.read_text())
        starts = [e for e in obj["traceEvents"] if e["ph"] == "s"]
        finishes = [e for e in obj["traceEvents"] if e["ph"] == "f"]
        assert {e["id"] for e in starts} == {e["id"] for e in finishes}
        assert all(e.get("bp") == "e" for e in finishes)
        # the merged file still validates (s/f are well-formed events)
        obs_trace.validate_trace(obj)

    def test_beyond_uncertainty_negative_phase_is_a_violation(self):
        # Claim a tiny-uncertainty offset that is wrong by 30ms: the
        # wire/ack segments go negative far beyond the claimed bound.
        meta = {"client3": {"0": {"offset_us": 0.0, "uncertainty_us": 5.0,
                                  "rtt_us": 10.0, "samples": 8,
                                  "accepted": 4}}}
        report = obs_causal.analyze(synth_trace(-30_000.0, clock_meta=meta))
        assert report["violations"]

    def test_cli_json_and_min_join_gate(self, tmp_path, capsys):
        from mpit_tpu.obs.__main__ import main as obs_cli

        path = tmp_path / "synth.json"
        path.write_text(json.dumps(synth_trace(500.0)))
        assert obs_cli(["analyze", str(path), "--json",
                        "--min-join", "0.95"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["ops"]["join_rate"] == 1.0
        assert payload["critical_path"]["client"] == 3
        # drop the server half: every completed op is unjoined => rc 1
        obj = synth_trace(500.0)
        obj["traceEvents"] = [
            e for e in obj["traceEvents"]
            if (e.get("args") or {}).get("side") != "server"
            and e.get("pid") != 0]
        path2 = tmp_path / "halved.json"
        path2.write_text(json.dumps(obj))
        assert obs_cli(["analyze", str(path2), "--min-join", "0.95"]) == 1


# ---------------------------------------------------------------------------
# real gangs: round trip, retries, legacy interop


def launch_timed_gang(nservers=2, nclients=2, client_plans=None,
                      client_ft=TIMED_FT):
    n = nservers + nclients
    router = LocalRouter(n)
    sranks, cranks = list(range(nservers)), list(range(nservers, n))
    servers, threads = [], []
    for r in sranks:
        servers.append(ParamServer(r, cranks, router.endpoint(r), rule="add",
                                   ft=FTConfig(rejoin=True)))
        threads.append(threading.Thread(target=servers[-1].start,
                                        daemon=True))
    for t in threads:
        t.start()
    clients, transports = [], []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        transports.append(ep)
        clients.append(ParamClient(r, sranks, ep,
                                   seed_servers=(r == cranks[0]),
                                   ft=client_ft))
    return servers, clients, threads, transports


def run_rounds(servers, clients, threads, rounds, size=64):
    rng = np.random.default_rng(7)
    starters, params = [], []
    for c in clients:
        p = (rng.normal(size=size).astype(np.float32)
             if not params else np.zeros(size, np.float32))
        params.append(p)
        starters.append(threading.Thread(
            target=c.start, args=(p, np.zeros(size, np.float32)),
            daemon=True))
    for t in starters:
        t.start()
    join_all(starters)
    for _ in range(rounds):
        for c in clients:
            c.async_recv_param()
            c.wait()
        for c in clients:
            c.grad[:] = rng.normal(size=size).astype(np.float32)
            c.async_send_grad()
            c.wait()
    for c in clients:
        c.stop()
    join_all(threads)


class TestGangRoundTrip:
    def test_timed_gang_trace_joins_and_decomposes(self, obs_on, tmp_path):
        """The acceptance scenario: a real 2s/2c gang on the FLAG_TIMING
        wire, trace exported and analyzed — every completed framed op
        joins, every phase is non-negative, sums hold, and the trace
        carries the wire-level estimator state."""
        servers, clients, threads, _ = launch_timed_gang()
        run_rounds(servers, clients, threads, rounds=4)
        path = str(tmp_path / "gang.json")
        obs_trace.write_rank_trace(path, rank=0, role="gang")
        report = obs_causal.analyze(path)
        assert report["ops"]["completed"] > 0
        assert report["ops"]["join_rate"] == 1.0
        assert report["violations"] == []
        # wire-level estimator state rode the trace (every client had
        # accepted exchanges against every server)
        sources = {(e["client"], e["server"]): e["source"]
                   for e in report["offsets"]}
        for c in (2, 3):
            for s in (0, 1):
                assert sources.get((c, s)) == "wire", sources
        for d in report["chains"]:
            assert all(v >= 0.0 for v in d["phases"].values())
            assert sum(d["phases"].values()) == pytest.approx(
                d["wall_us"], abs=max(d["uncertainty_us"], 1.0) + 1.0)
        # both halves' stamps landed on the client spans
        obj = json.load(open(path))
        stamped = [e for e in obj["traceEvents"]
                   if e["ph"] == "B" and "srv_recv_us" in
                   (e.get("args") or {})]
        assert stamped
        assert (obj["otherData"]["clock"].keys()
                >= {"client2", "client3"})

    def test_estimator_offset_near_zero_same_process(self, obs_on):
        """All ranks share one process => true offset is 0; the
        estimator must land within its own uncertainty (and sane
        absolute bounds)."""
        servers, clients, threads, _ = launch_timed_gang()
        run_rounds(servers, clients, threads, rounds=4)
        for c in clients:
            for srank in (0, 1):
                clock = c._clock.peers[srank]
                assert clock.accepted > 0
                assert abs(clock.offset_us) <= clock.uncertainty_us + 1.0
        # the clock gauge surfaced
        keys = [k for k in obs_on.snapshot()
                if k.startswith("mpit_clock_offset_us")]
        assert len(keys) == 4  # 2 clients x 2 servers


def simulate_grad_channel(plan, src, dst, rounds):
    """Replay the plan arithmetic for one client->server GRAD channel
    (the test_obs.py harness contract): dropped frames time out and
    resend; passed/duplicated frames ack."""
    sends = drops = dups = 0
    n = 0
    for _ in range(rounds):
        while True:
            n += 1
            sends += 1
            verdict = plan.decide(src, dst, tags.GRAD, n)
            if verdict == "drop":
                drops += 1
                continue
            if verdict == "dup":
                dups += 1
            break
    return sends, drops, dups


class TestDropPlanAttempts:
    def test_retry_attempts_appear_as_separate_attempt_chains(
            self, obs_on, tmp_path):
        """Every-2nd GRAD dropped on client 0's channels: each dropped
        op's chain must carry exactly 1 + resends attempt segments (the
        backoff marks split them), matching the replayed plan
        arithmetic — and the analyzer attributes the dead attempts to
        the ``retry`` phase."""
        rounds, nservers = 4, 2
        plans = {0: FaultPlan(seed=0, drop_every=2,
                              tags=frozenset({tags.GRAD}))}
        servers, clients, threads, transports = launch_timed_gang(
            client_plans=plans)
        run_rounds(servers, clients, threads, rounds)
        want_retries = sum(
            simulate_grad_channel(plans[0], clients[0].rank, dst, rounds)[1]
            for dst in range(nservers))
        assert clients[0].retries == want_retries > 0
        path = str(tmp_path / "drop.json")
        obs_trace.write_rank_trace(path, rank=0, role="gang")
        events, _ = obs_causal.load_trace(path)
        chains, _ = obs_causal.join_spans(obs_causal.extract_spans(events))
        grad_chains = [c for c in chains
                       if c.op == "GRAD" and c.key[1] == clients[0].rank]
        assert grad_chains
        retried = [c for c in grad_chains
                   if c.client.args.get("retries", 0) >= 1]
        assert retried, "the drop plan produced no retried GRAD chain"
        total_attempts = 0
        for chain in grad_chains:
            attempts = chain.attempts()
            assert len(attempts) == 1 + int(
                chain.client.args.get("retries", 0) or 0)
            assert chain.joined  # the surviving attempt reached a server
            total_attempts += len(attempts)
        n_ops = rounds * nservers
        assert total_attempts == n_ops + want_retries
        report = obs_causal.analyze(path)
        assert report["violations"] == []
        by_key = {(d["client"], d["server"], d["seq"]): d
                  for d in report["chains"] if d["op"] == "GRAD"}
        for chain in retried:
            d = by_key[(chain.key[1], chain.key[2][1], chain.key[4])]
            assert d["phases"]["retry"] > 0.0


class TestLegacyInterop:
    def test_legacy_peers_negotiate_timing_off_per_pair(self, obs_on):
        """Mixed gang: a FLAG_TIMING client and a plain legacy (v1)
        client on the same servers.  The extension is per pair — the
        legacy pair's acks stay 16-byte [epoch, seq]-free legacy wire
        (2-word ack staging, no echo service), only the timed client
        grows estimator state, and the gang completes with every grad
        applied."""
        rounds, nservers = 2, 2
        n = nservers + 2
        router = LocalRouter(n)
        sranks, cranks = list(range(nservers)), list(range(nservers, n))
        servers, threads = [], []
        for r in sranks:
            servers.append(ParamServer(r, cranks, router.endpoint(r),
                                       rule="add", ft=FTConfig(rejoin=True)))
            threads.append(threading.Thread(target=servers[-1].start,
                                            daemon=True))
        for t in threads:
            t.start()
        clients = [
            ParamClient(cranks[0], sranks, router.endpoint(cranks[0]),
                        seed_servers=True, ft=TIMED_FT),
            ParamClient(cranks[1], sranks, router.endpoint(cranks[1]),
                        seed_servers=False, ft=FTConfig()),  # legacy v1
        ]
        assert clients[0]._timing and clients[0]._hdr == 24
        assert clients[0]._hdr_rx == 40
        assert not clients[1]._timing and clients[1]._hdr == 0
        run_rounds(servers, clients, threads, rounds)
        for s in servers:
            assert s._timing[cranks[0]] is True
            assert s._timing.get(cranks[1], False) is False
            # ack staging sized per negotiation: timing tail vs legacy
            assert s._ack_send[cranks[0]].size == ACK_TIMING_WORDS
            assert cranks[1] not in s._ack_send  # legacy: 0-byte acks
        assert clients[0]._clock.peers and all(
            c.accepted for c in clients[0]._clock.peers.values())
        assert not clients[1]._clock.peers
        assert (sum(s.grads_applied for s in servers)
                == rounds * 2 * nservers)

    def test_heartbeat_echo_refreshes_clock_while_idle(self, obs_on):
        """Beats flow during ping()/wait() even with no op in flight;
        with FLAG_TIMING each is echoed and the estimator accumulates
        samples from the heartbeat stream alone."""
        import time as _time

        ft = FTConfig(op_deadline_s=0.25, heartbeat_s=0.01, timing=True,
                      backoff_base_s=0.005, backoff_cap_s=0.02)
        servers, clients, threads, _ = launch_timed_gang(client_ft=ft)
        run_rounds_started = False
        try:
            rng = np.random.default_rng(7)
            starters, params = [], []
            for c in clients:
                p = (rng.normal(size=64).astype(np.float32)
                     if not params else np.zeros(64, np.float32))
                params.append(p)
                starters.append(threading.Thread(
                    target=c.start, args=(p, np.zeros(64, np.float32)),
                    daemon=True))
            for t in starters:
                t.start()
            join_all(starters)
            run_rounds_started = True
            before = {s: clients[0]._clock.peer(s).samples for s in (0, 1)}
            deadline = _time.monotonic() + 5.0
            while _time.monotonic() < deadline:
                for c in clients:
                    c.ping()
                if all(clients[0]._clock.peer(s).samples > before[s] + 2
                       for s in (0, 1)):
                    break
                _time.sleep(0.002)
            for s in (0, 1):
                assert clients[0]._clock.peer(s).samples > before[s], \
                    "no heartbeat-echo clock samples while idle"
        finally:
            if run_rounds_started:
                for c in clients:
                    c.stop()
                join_all(threads)


# ---------------------------------------------------------------------------
# flight-dump causal chain + top columns


class TestFlightCausalChain:
    def test_open_op_marks_and_clock_ride_the_dump(self, obs_on, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("MPIT_OBS_FLIGHT", str(tmp_path))
        rec = obs.get_recorder()
        span = rec.op("GRAD", peer=0, side="client", rank=3, epoch=0, seq=9)
        span.mark("encode")
        span.mark("send")
        span.mark("backoff")
        est = obs_clock.ClockEstimator()
        est.add_exchange(0, 1_000_000, 1_000_100, 1_000_110, 1_000_210)
        obs_clock.register("client3", est)
        flight = obs.get_flight()
        path = flight.dump("stall_test")
        span.end("exhausted")
        dump = json.load(open(path))
        (op,) = [o for o in dump["inflight_ops"] if o["op"] == "GRAD"]
        assert [m[0] for m in op["marks"]] == ["encode", "send", "backoff"]
        assert all(isinstance(m[1], float) for m in op["marks"])
        assert op["phase"] == "backoff" and op["seq"] == 9
        assert dump["clock"]["client3"]["0"]["accepted"] == 1
        obs.validate_dump(path)  # schema stays valid with the additions


class TestTopColumns:
    def test_hist_quantile_from_exposition(self):
        from mpit_tpu.obs import top as obs_top
        from mpit_tpu.obs.metrics import Registry

        reg = Registry()
        h = reg.histogram("mpit_ps_op_seconds", op="GRAD", side="client")
        for v in [0.001] * 98 + [3.0, 3.5]:
            h.observe(v)
        samples = obs_top.parse_exposition(reg.exposition())
        p50 = obs_top.hist_quantile(samples, "mpit_ps_op_seconds", 0.50)
        p99 = obs_top.hist_quantile(samples, "mpit_ps_op_seconds", 0.99)
        assert p50 is not None and p50 <= 0.002
        assert p99 is not None and p99 >= 2.0
        assert obs_top.hist_quantile(samples, "mpit_nonexistent", 0.99) is None

    def test_rank_row_has_p99_and_sendq_columns(self):
        from mpit_tpu.obs import top as obs_top
        from mpit_tpu.obs.metrics import Registry

        reg = Registry()
        reg.histogram("mpit_ps_op_seconds", op="GRAD",
                      side="client").observe(0.004)
        reg.gauge("mpit_tcp_send_queue_depth", rank=1, peer=0).set(3)
        reg.gauge("mpit_tcp_send_queue_depth", rank=1, peer=2).set(4)
        sample = {"metrics": obs_top.parse_exposition(reg.exposition()),
                  "status": {"role": "worker"}, "port": 1}
        row = obs_top._rank_row(1, sample, None, None)
        assert row["p99_s"] is not None and row["p99_s"] >= 0.004
        assert row["send_queue"] == 7
        table = obs_top.render_table([row])
        assert "p99ms" in table and "sendq" in table
