"""Tests for the native C++ shm transport: in-process endpoint pairs, the
chunking path, and real multi-process runs (the mpirun-analog shape).
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from mpit_tpu.comm.shm import ShmTransport

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pair(ns, ring_bytes=1 << 20):
    return (
        ShmTransport(ns, 0, 2, ring_bytes=ring_bytes),
        ShmTransport(ns, 1, 2, ring_bytes=ring_bytes),
    )


class TestShmTransport:
    def test_roundtrip_array(self):
        a, b = pair(f"t_rt_{os.getpid()}")
        try:
            data = np.arange(32, dtype=np.float32)
            a.send(data, 1, 3)
            out = np.zeros_like(data)
            b.recv(0, 3, out=out)
            np.testing.assert_array_equal(out, data)
        finally:
            a.close()
            b.close()

    def test_payload_without_buffer(self):
        a, b = pair(f"t_nb_{os.getpid()}")
        try:
            a.send(b"hello-wire", 1, 9)
            while not b.iprobe(0, 9):
                pass
            assert b.recv(0, 9) == b"hello-wire"
        finally:
            a.close()
            b.close()

    def test_chunked_larger_than_ring(self):
        """5 MB message through a 1 MB ring: chunks stream as the receiver
        drains — the path 640 MB reference payloads rely on (ptest.lua:3)."""
        a, b = pair(f"t_ch_{os.getpid()}")
        try:
            big = np.random.default_rng(0).standard_normal(5 * 1024 * 128)
            hs = a.isend(big, 1, 4)
            out = np.zeros_like(big)
            hr = b.irecv(0, 4, out=out)
            spins = 0
            # Poll BOTH sides each round: the sender can only finish as the
            # receiver drains the ring (message is 5x the ring size).
            while True:
                send_done = a.test(hs)
                recv_done = b.test(hr)
                if send_done and recv_done:
                    break
                spins += 1
                assert spins < 10**6
            np.testing.assert_array_equal(out, big)
        finally:
            a.close()
            b.close()

    def test_zero_byte_header_ack(self):
        a, b = pair(f"t_zb_{os.getpid()}")
        try:
            a.send(b"", 1, 5)
            assert b.iprobe(0, 5)
            assert b.recv(0, 5) == b""
        finally:
            a.close()
            b.close()

    def test_size_mismatch_raises(self):
        a, b = pair(f"t_sm_{os.getpid()}")
        try:
            a.send(np.ones(4, np.float32), 1, 6)
            while not b.iprobe(0, 6):
                pass
            handle = b.irecv(0, 6, out=np.zeros(3, np.float32))
            with pytest.raises(ValueError, match="size mismatch"):
                while not b.test(handle):
                    pass
        finally:
            a.close()
            b.close()

    def test_tag_isolation(self):
        a, b = pair(f"t_ti_{os.getpid()}")
        try:
            a.send(np.full(2, 1.0, np.float32), 1, 11)
            a.send(np.full(2, 2.0, np.float32), 1, 12)
            out12 = np.zeros(2, np.float32)
            b.recv(0, 12, out=out12)  # later tag first: no head-of-line block
            out11 = np.zeros(2, np.float32)
            b.recv(0, 11, out=out11)
            assert out12[0] == 2.0 and out11[0] == 1.0
        finally:
            a.close()
            b.close()

    def test_fifo_per_channel(self):
        a, b = pair(f"t_ff_{os.getpid()}")
        try:
            for i in range(5):
                a.send(np.full(1, float(i), np.float32), 1, 7)
            got = []
            for _ in range(5):
                out = np.zeros(1, np.float32)
                b.recv(0, 7, out=out)
                got.append(float(out[0]))
            assert got == [0.0, 1.0, 2.0, 3.0, 4.0]
        finally:
            a.close()
            b.close()

    def test_cancel_releases(self):
        a, b = pair(f"t_cx_{os.getpid()}")
        try:
            handle = b.irecv(0, 99, out=np.zeros(1, np.float32))
            b.cancel(handle)
            assert handle.cancelled and not b.test(handle)
        finally:
            a.close()
            b.close()

    def test_wtime_monotonic(self):
        t0 = ShmTransport.wtime()
        t1 = ShmTransport.wtime()
        assert t1 >= t0


class TestShmCancelAndProbe:
    """Focused coverage for ShmTransport.cancel/iprobe (comm/shm.py) —
    the shutdown path (reference init.lua:50-58) and the probe-then-recv
    rendezvous the aio schedulers rely on."""

    def test_iprobe_lifecycle(self):
        """False before arrival, true once assembled, false after the
        matching recv drains it."""
        a, b = pair(f"t_ip_{os.getpid()}")
        try:
            assert not b.iprobe(0, 31)
            a.send(np.ones(4, np.float32), 1, 31)
            while not b.iprobe(0, 31):
                pass
            assert b.iprobe(0, 31)  # idempotent: probing consumes nothing
            out = np.zeros(4, np.float32)
            b.recv(0, 31, out=out)
            assert not b.iprobe(0, 31)
        finally:
            a.close()
            b.close()

    def test_iprobe_is_src_and_tag_selective(self):
        a, b = pair(f"t_is_{os.getpid()}")
        try:
            a.send(b"x", 1, 41)
            while not b.iprobe(0, 41):
                pass
            assert not b.iprobe(0, 42)  # different tag
            assert not a.iprobe(1, 41)  # different endpoint/direction
        finally:
            a.close()
            b.close()

    def test_cancelled_recv_leaves_message_for_next_recv(self):
        """cancel releases the native op; the queued message must still
        serve a later correctly-posted receive."""
        a, b = pair(f"t_cl_{os.getpid()}")
        try:
            pending = b.irecv(0, 51, out=np.zeros(2, np.float32))
            b.cancel(pending)
            a.send(np.asarray([3.0, 4.0], np.float32), 1, 51)
            out = np.zeros(2, np.float32)
            b.recv(0, 51, out=out)
            np.testing.assert_array_equal(out, [3.0, 4.0])
            assert pending.cancelled and not b.test(pending)
        finally:
            a.close()
            b.close()

    def test_cancel_after_completion_keeps_done(self):
        """cancel on a tested-done handle is a no-op for correctness:
        test stays True (idempotent completion caching) and nothing
        double-releases natively."""
        a, b = pair(f"t_cd_{os.getpid()}")
        try:
            data = np.ones(2, np.float32)
            hs = a.isend(data, 1, 61)
            out = np.zeros(2, np.float32)
            hr = b.irecv(0, 61, out=out)
            while not (a.test(hs) and b.test(hr)):
                pass
            a.cancel(hs)
            b.cancel(hr)
            assert a.test(hs) and b.test(hr)
            np.testing.assert_array_equal(out, data)
        finally:
            a.close()
            b.close()

    def test_cancelled_send_ownership_released(self):
        """cancel drops the transport's buffer reference (the liveness
        contract's release half) and test reports not-done."""
        a, b = pair(f"t_co_{os.getpid()}")
        try:
            # Clog the 64 KiB ring so the second send stays in flight.
            big = np.ones(1 << 16, np.uint8)
            h1 = a.isend(big, 1, 71)
            h2 = a.isend(np.ones(8, np.float32), 1, 72)
            a.cancel(h2)
            assert h2.cancelled and h2.buf is None
            assert not a.test(h2)
            # The clogged first message still completes once drained.
            out = np.zeros(1 << 16, np.uint8)
            b.recv(0, 71, out=out)
            while not a.test(h1):
                pass
        finally:
            a.close()
            b.close()

    def test_non_contiguous_send_rejected(self):
        """Satellite regression (zero-copy rule): the shm transport must
        refuse a non-contiguous send buffer like as_bytes_view does, not
        silently detach from the caller's memory."""
        a, b = pair(f"t_nc_{os.getpid()}")
        try:
            with pytest.raises(ValueError, match="C-contiguous"):
                a.isend(np.arange(16, dtype=np.float32)[::2], 1, 81)
        finally:
            a.close()
            b.close()


ECHO_PEER = textwrap.dedent(
    """
    import sys, numpy as np
    sys.path.insert(0, {repo!r})
    from mpit_tpu.comm.shm import ShmTransport
    t = ShmTransport({ns!r}, 1, 2)
    out = np.zeros({n}, np.float32)
    t.recv(0, 21, out=out)
    t.send(out * 2.0, 0, 22)
    # hold until the send drains for sure (send() already blocks on test)
    t.close()
    """
)


class TestMultiProcess:
    def test_cross_process_echo(self):
        ns = f"t_mp_{os.getpid()}"
        n = 4096
        main = ShmTransport(ns, 0, 2)
        try:
            peer = subprocess.Popen(
                [sys.executable, "-c", ECHO_PEER.format(repo=REPO, ns=ns, n=n)],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            data = np.linspace(0, 1, n, dtype=np.float32)
            main.send(data, 1, 21)
            out = np.zeros(n, np.float32)
            main.recv(1, 22, out=out)
            np.testing.assert_allclose(out, data * 2.0, rtol=1e-6)
            assert peer.wait(60) == 0
        finally:
            main.close()
