"""mesh_launch CLI on the 8-virtual-device mesh: both optimizers train
(loss decreases, errors finite) and the result contract holds."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from mpit_tpu.train.mesh_launch import MESH_LAUNCH_DEFAULTS, run


def _tiny_cfg(**kw):
    base = dict(model="linear", side=8, epochs=2, batch=32,
                target_test_err=0.5)
    base.update(kw)
    return MESH_LAUNCH_DEFAULTS.merged(base)


def test_easgd_trains():
    res = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, lr=0.1, mom=0.9))
    assert len(res["history"]) == 2
    errs = [h["test_err"] for h in res["history"]]
    assert all(np.isfinite(e) for e in errs)
    assert res["history"][-1]["avg_loss"] < res["history"][0]["avg_loss"] * 1.5
    assert res["mesh"]["dp"] * res["mesh"]["shard"] == 8
    assert res["processes"] == 1


def test_syncdp_trains_to_target():
    res = run(_tiny_cfg(opt="syncdp", lr=0.2, mom=0.9, batch=128,
                        target_test_err=0.3, epochs=3))
    assert res["final_test_err"] < 0.3
    assert res["time_to_target"] is not None


@pytest.mark.parametrize("opt,kw", [
    ("easgd", dict(su=2, mva=0.2, lr=0.1, mom=0.9)),
    ("syncdp", dict(lr=0.2, mom=0.9, batch=64)),
])
def test_device_stream_trains_identically(opt, kw):
    """Staging an epoch in HBM — and collapsing it into one jitted scan
    — must change where/how batches are dispatched, not what is trained:
    same seed -> same per-epoch losses and errors as the per-step host
    path, for both the scan (epoch_scan=1, default) and step-loop
    (epoch_scan=0) staged variants."""
    host = run(_tiny_cfg(opt=opt, **kw))
    scan = run(_tiny_cfg(opt=opt, device_stream=1, **kw))
    steploop = run(_tiny_cfg(opt=opt, device_stream=1, epoch_scan=0, **kw))
    for variant in (scan, steploop):
        for h, s in zip(host["history"], variant["history"]):
            np.testing.assert_allclose(s["avg_loss"], h["avg_loss"],
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(s["test_err"], h["test_err"],
                                       atol=1e-6)


def test_measure_throughput_reports_steady_rate():
    res = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, lr=0.1, mom=0.9,
                        epochs=1, measure_throughput=1))
    assert res["samples_per_sec_steady"] is not None
    assert res["samples_per_sec_steady"] > 0


def test_device_loop_stops_at_target_after_one_epoch():
    """device_loop=1: the while_loop's on-device early exit — a target
    any first epoch meets must end the program after exactly one epoch,
    with a real time_to_target."""
    res = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, lr=0.1, mom=0.9,
                        epochs=6, device_loop=1, stop_at_target=1,
                        target_test_err=0.95))
    assert len(res["history"]) == 1
    assert res["time_to_target"] is not None
    assert res["history"][0]["at"] is not None


def test_device_loop_runs_all_epochs_and_learns():
    res = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, lr=0.1, mom=0.9,
                        epochs=3, device_loop=1))
    assert [h["epoch"] for h in res["history"]] == [0, 1, 2]
    errs = [h["test_err"] for h in res["history"]]
    assert all(np.isfinite(e) for e in errs)
    # Learns: linear model on digits drops well under chance in 3 epochs.
    assert res["final_test_err"] < 0.5
    assert res["samples_per_sec"] and res["samples_per_sec"] > 0
    # Only the final wall timestamp is real (one dispatch ran them all).
    assert res["history"][-1]["at"] is not None
    assert all(h["at"] is None for h in res["history"][:-1])
    # No target stop requested -> no time_to_target claim.
    assert res["time_to_target"] is None


def test_device_loop_syncdp_smoke():
    res = run(_tiny_cfg(opt="syncdp", lr=0.2, mom=0.9, batch=64,
                        epochs=2, device_loop=1))
    assert len(res["history"]) == 2
    assert np.isfinite(res["final_test_err"])


def test_device_loop_mid_run_target_without_stop_is_none():
    """Contract difference, pinned: with stop_at_target=0 and a target
    met mid-run, the host loop reports time_to_target at that epoch but
    device_loop returns None (no per-epoch wall timestamps exist inside
    one device program; run() logs a warning naming the fix).  A caller
    toggling modes must see the difference, not a silently shifted
    number."""
    kw = dict(opt="easgd", su=2, mva=0.2, lr=0.1, mom=0.9,
              epochs=3, stop_at_target=0, target_test_err=0.95)
    host = run(_tiny_cfg(**kw))
    dev = run(_tiny_cfg(device_loop=1, **kw))
    assert host["time_to_target"] is not None
    assert dev["time_to_target"] is None


def test_train_wall_mode_reported():
    host = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=1))
    dev = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=1,
                        device_loop=1))
    assert host["train_wall_mode"] == "host_loop"
    assert dev["train_wall_mode"] == "device_loop"


def test_device_loop_resyncs_schedule_via_set_steps(monkeypatch):
    """device_loop must hand the device-advanced schedule back to the
    trainer through trainer-owned set_steps — spied here so the resync
    (and its epoch*steps argument) is guarded on every default CI run
    without paying the throughput leg's timing loop."""
    from mpit_tpu.parallel.easgd import MeshEASGD

    calls = []
    orig = MeshEASGD.set_steps
    monkeypatch.setattr(
        MeshEASGD, "set_steps",
        lambda self, n: (calls.append(n), orig(self, n))[1])
    res = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=2,
                        device_loop=1))
    assert len(calls) == 1
    # epochs_ran * steps_per_epoch, and the counter really moved.
    assert calls[0] > 0
    assert calls[0] % len(res["history"]) == 0


@pytest.mark.slow
def test_device_loop_then_throughput_leg():
    """The bench.py flow: device_loop training followed by the
    measure_throughput leg — the resynced schedule must let the steady
    leg run the already-compiled programs."""
    res = run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=2,
                        device_loop=1, measure_throughput=1))
    assert res["samples_per_sec_steady"] is not None


def test_device_loop_rejects_ckpt_and_resume(tmp_path):
    with pytest.raises(ValueError, match="device_loop"):
        run(_tiny_cfg(opt="easgd", device_loop=1, ckpt_dir=str(tmp_path)))
    with pytest.raises(ValueError, match="device_loop"):
        run(_tiny_cfg(opt="easgd", device_loop=1, resume="auto",
                      ckpt_dir=str(tmp_path)))


def test_checkpoint_resume_matches_straight_run(tmp_path):
    """2 epochs + resume for 2 more must reproduce the straight 4-epoch
    run exactly: same data order (burned permutations), same losses."""
    kw = dict(opt="easgd", su=2, mva=0.2, lr=0.1, mom=0.9)
    straight = run(_tiny_cfg(epochs=4, **kw))
    run(_tiny_cfg(epochs=2, ckpt_dir=str(tmp_path), **kw))
    resumed = run(_tiny_cfg(epochs=4, resume="auto",
                            ckpt_dir=str(tmp_path), **kw))
    assert [h["epoch"] for h in resumed["history"]] == [2, 3]
    for h_s, h_r in zip(straight["history"][2:], resumed["history"]):
        np.testing.assert_allclose(h_r["avg_loss"], h_s["avg_loss"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(h_r["test_err"], h_s["test_err"],
                                   atol=1e-6)


def test_resume_guards(tmp_path):
    run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=1,
                  ckpt_dir=str(tmp_path)))
    with pytest.raises(ValueError, match="seed"):
        run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=2, seed=99,
                      resume="auto", ckpt_dir=str(tmp_path)))
    with pytest.raises(ValueError, match="requires --ckpt_dir"):
        run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=2, resume="auto"))


def test_resume_shape_mismatch_fails_loudly(tmp_path):
    run(_tiny_cfg(opt="easgd", su=2, mva=0.2, epochs=1,
                  ckpt_dir=str(tmp_path)))
    with pytest.raises(ValueError, match="keys|shape"):
        run(_tiny_cfg(opt="syncdp", epochs=2, resume="auto",
                      ckpt_dir=str(tmp_path)))


@pytest.mark.slow
def test_two_process_distributed_train_ckpt_resume(tmp_path):
    """Real multi-process jax.distributed end to end: two OS processes,
    4 virtual CPU devices each, form one 8-device mesh, train EASGD with
    per-process local batch rows, checkpoint via the orbax backend, and
    resume.  This is the multi-host path the CLI advertises."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def launch(extra):
        procs = []
        for pid in (0, 1):
            env = dict(
                os.environ,
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4",
                PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "mpit_tpu.train.mesh_launch",
                 "--model", "linear", "--side", "8", "--batch", "32",
                 "--opt", "easgd", "--su", "2", "--mva", "0.2",
                 "--lr", "0.1", "--mom", "0.9",
                 "--coordinator", f"127.0.0.1:{port}",
                 "--num_processes", "2", "--process_id", str(pid),
                 "--ckpt_dir", str(tmp_path)] + extra,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
                text=True,
            ))
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, f"rank failed:\n{err[-3000:]}"
            outs.append(out)
        return outs

    outs = launch(["--epochs", "2"])
    res = json.loads(outs[0][outs[0].index("{"):])
    assert res["processes"] == 2
    assert res["mesh"]["dp"] * res["mesh"]["shard"] == 8
    assert all(np.isfinite(h["test_err"]) for h in res["history"])
    assert (tmp_path / "step_1").exists()  # orbax backend, not npz

    outs = launch(["--epochs", "4", "--resume", "auto"])
    res2 = json.loads(outs[0][outs[0].index("{"):])
    assert [h["epoch"] for h in res2["history"]] == [2, 3]


def test_bad_opt_raises():
    with pytest.raises(ValueError, match="easgd|syncdp"):
        run(_tiny_cfg(opt="adamw"))


def test_explicit_mesh_shape():
    res = run(_tiny_cfg(opt="syncdp", dp=4, shard=2, epochs=1))
    assert res["mesh"] == {"dp": 4, "shard": 2}
