"""mpit_tpu.shardctl — versioned maps, rebalancing, live migration.

The acceptance invariants (ISSUE 5): live migration and lease-expiry
shard failover both leave final params **bitwise equal** to a fault-free
static-map run — including under deterministic drop/dup fault plans —
because the shard-scoped dedup state travels with the shard, re-routed
retries admit at-most-once on the new owner, and lockstep turns pin the
cross-client apply order (same discipline as tests/test_ft.py).
"""

import threading
import tempfile

import numpy as np
import pytest

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import FaultPlan, FaultyTransport, FTConfig
from mpit_tpu.ps import ParamClient, ParamServer, Shard, tags, weighted_layout
from mpit_tpu.shardctl import (
    RebalancePolicy,
    ShardController,
    ShardLoad,
    ShardMap,
)
from mpit_tpu.shardctl import wire as scwire

DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})
REPLY_TAGS = frozenset({tags.GRAD_ACK, tags.PARAM, tags.PARAM_PUSH_ACK})

FAST_FT = FTConfig(op_deadline_s=0.3, max_retries=10,
                   backoff_base_s=0.005, backoff_cap_s=0.02)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# weighted_layout — Hypothesis-style property sweep (satellite)


class TestWeightedLayout:
    def _check_invariants(self, plong, shards):
        assert shards, "layout produced no shards"
        assert shards[0].offset == 0
        for prev, cur in zip(shards, shards[1:]):
            assert cur.offset == prev.end, "shards must be contiguous"
        assert shards[-1].end == plong, "shards must cover the range"
        assert all(s.size >= 1 for s in shards), "every shard nonempty"

    def test_property_sweep(self):
        """Cover-the-range / nonempty / contiguous over a seeded sweep of
        (plong, n, weights) samples — the property-test satellite."""
        rng = np.random.default_rng(1234)
        for _ in range(300):
            n = int(rng.integers(1, 9))
            plong = int(rng.integers(n, 5000))
            weights = rng.uniform(0.01, 10.0, size=n).tolist()
            shards = weighted_layout(plong, weights)
            self._check_invariants(plong, shards)
            assert len(shards) == n

    def test_proportionality(self):
        shards = weighted_layout(1000, [1.0, 3.0])
        assert shards == [Shard(0, 250), Shard(250, 750)]

    def test_remainder_goes_to_heaviest(self):
        # floors: [333, 111, 556] leave 1 spare -> heaviest (rank 2)
        shards = weighted_layout(1001, [3.0, 1.0, 5.0])
        assert sum(s.size for s in shards) == 1001
        assert shards[2].size == 557

    def test_tiny_plong_keeps_everyone_nonempty(self):
        shards = weighted_layout(3, [100.0, 0.01, 0.01])
        self._check_invariants(3, shards)

    def test_errors(self):
        with pytest.raises(ValueError):
            weighted_layout(2, [1.0, 1.0, 1.0])
        with pytest.raises(ValueError):
            weighted_layout(10, [])
        with pytest.raises(ValueError):
            weighted_layout(10, [1.0, -1.0])


# ---------------------------------------------------------------------------
# ShardMap


class TestShardMap:
    def test_initial_matches_shard_layout(self):
        m = ShardMap.initial(10, [0, 1, 2])
        assert [e.shard for e in m.entries] == [
            Shard(0, 3), Shard(3, 3), Shard(6, 4)]
        assert m.version == 0 and m.owners() == [0, 1, 2]

    def test_weighted_initial(self):
        m = ShardMap.initial(100, [5, 7], weights=[1.0, 3.0])
        assert m.entry(1).shard.size == 75 and m.owner(1) == 7

    def test_moved_bumps_version_only(self):
        m = ShardMap.initial(10, [0, 1])
        m2 = m.moved(1, 0)
        assert (m2.version, m2.owner(1)) == (1, 0)
        assert m.version == 0 and m.owner(1) == 1  # immutability
        assert [e.shard for e in m2.entries] == [e.shard for e in m.entries]

    def test_reassigned_spreads_over_survivors(self):
        m = ShardMap.initial(30, [0, 1, 2])
        m2 = m.moved(0, 1)  # rank 1 holds shards 0 and 1
        m3 = m2.reassigned(1, [0, 2])
        assert m3.version == m2.version + 1
        # both orphans land on survivors and no survivor exceeds 2 shards
        assert {m3.owner(0), m3.owner(1)} <= {0, 2}
        assert max(len(m3.shards_of(r)) for r in (0, 2)) == 2

    def test_wire_roundtrip(self):
        m = ShardMap.initial(1000, [3, 5, 9]).moved(2, 3)
        again = ShardMap.from_wire(m.to_wire())
        assert again == m
        with pytest.raises(ValueError):
            ShardMap.from_wire(np.asarray([1, 2, 3, 4], np.int64))

    def test_tiling_validated(self):
        from mpit_tpu.shardctl.shardmap import ShardEntry

        with pytest.raises(ValueError, match="tile"):
            ShardMap(0, 10, [ShardEntry(0, Shard(0, 4), 0),
                             ShardEntry(1, Shard(5, 5), 1)])


# ---------------------------------------------------------------------------
# policy


class TestRebalancePolicy:
    def _loads(self, busy):
        return {rank: {sid: ShardLoad(ops=10, busy_s=b)
                       for sid, b in shards.items()}
                for rank, shards in busy.items()}

    def test_proposes_hot_to_cold(self):
        m = ShardMap.initial(100, [0, 1])
        policy = RebalancePolicy(ratio=3.0, min_busy_s=0.01)
        loads = self._loads({0: {0: 1.0}, 1: {1: 0.1}})
        assert policy.propose(m, loads) == (0, 1)

    def test_quiet_window_proposes_nothing(self):
        m = ShardMap.initial(100, [0, 1])
        policy = RebalancePolicy(ratio=3.0, min_busy_s=0.5)
        loads = self._loads({0: {0: 0.4}, 1: {1: 0.01}})
        assert policy.propose(m, loads) is None

    def test_balanced_load_proposes_nothing(self):
        m = ShardMap.initial(100, [0, 1])
        policy = RebalancePolicy(ratio=3.0, min_busy_s=0.01)
        loads = self._loads({0: {0: 1.0}, 1: {1: 0.9}})
        assert policy.propose(m, loads) is None

    def test_disabled_policy_is_silent(self):
        m = ShardMap.initial(100, [0, 1])
        policy = RebalancePolicy(enabled=False)
        assert policy.propose(m, self._loads({0: {0: 9.0}, 1: {1: 0.0}})) \
            is None


# ---------------------------------------------------------------------------
# gang harness


def launch_sc(nservers, nclients, size, ckpt_dir=None, codec=None,
              client_plans=None, server_plan=None, client_ft=FAST_FT,
              server_ft=FAST_FT, ctl_kwargs=None):
    """Shardctl topology: servers + controller threads wired over the
    in-process router, clients driven by the test (lockstep turns)."""
    n = nservers + nclients + 1
    router = LocalRouter(n)
    sranks = list(range(nservers))
    cranks = list(range(nservers, nservers + nclients))
    ctl_rank = n - 1
    servers, threads = [], []
    for r in sranks:
        ep = router.endpoint(r)
        if server_plan is not None:
            ep = FaultyTransport(ep, server_plan)
        servers.append(ParamServer(
            r, cranks, ep, rule="add", ft=server_ft,
            controller_rank=ctl_rank, ckpt_dir=ckpt_dir,
            ckpt_interval=1e9))
        threads.append(threading.Thread(target=servers[-1].start,
                                        daemon=True))
    for t in threads:
        t.start()
    ctl = ShardController(ctl_rank, router.endpoint(ctl_rank), sranks,
                          cranks, **(ctl_kwargs or {}))
    clients = []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        clients.append(ParamClient(
            r, sranks, ep, seed_servers=(r == cranks[0]), codec=codec,
            ft=client_ft, shardctl=True, controller_rank=ctl_rank))
    return servers, clients, threads, ctl


def start_clients(clients, w0):
    params, grads, starters = [], [], []
    for c in clients:
        p = w0.copy() if not params else np.zeros_like(w0)
        g = np.zeros_like(w0)
        params.append(p)
        grads.append(g)
        starters.append(threading.Thread(target=c.start, args=(p, g),
                                         daemon=True))
    for t in starters:
        t.start()
    join_all(starters)
    return params


def lockstep(clients, gtab, rounds, hook=None):
    for r in range(rounds):
        if hook is not None:
            hook(r)
        for i, c in enumerate(clients):
            c.grad[:] = gtab[i, r]
            c.async_send_grad()
            c.wait()


def finish(clients, threads, ctl, live_threads=None):
    clients[0].async_recv_param()
    clients[0].wait()
    out = clients[0].param.copy()
    for c in clients:
        c.stop()
    join_all(live_threads if live_threads is not None else threads)
    ctl.pump()
    assert ctl.done, "controller missed client STOPs"
    return out


# ---------------------------------------------------------------------------
# end-to-end: static parity, live migration, failover — all bitwise


class TestShardctlGang:
    def _tables(self, size=48, rounds=6, nclients=2, seed=7):
        rng = np.random.default_rng(seed)
        w0 = rng.normal(size=size).astype(np.float32)
        gtab = rng.normal(size=(nclients, rounds, size)).astype(np.float32)
        return w0, gtab

    def _run(self, w0, gtab, rounds, hook=None, **kw):
        servers, clients, threads, ctl = launch_sc(2, 2, len(w0), **kw)
        start_clients(clients, w0)
        ctl.pump()  # adopt the seeder's initial map
        assert ctl.smap is not None and ctl.smap.version == 0
        lockstep(clients, gtab, rounds,
                 hook=(lambda r: hook(r, ctl, servers, threads))
                 if hook else None)
        dead = [i for i, t in enumerate(threads) if not t.is_alive()]
        live = [t for t in threads if t.is_alive() or True]
        out = finish(clients, threads, ctl,
                     live_threads=[t for i, t in enumerate(threads)
                                   if i not in dead])
        return out, servers, clients, ctl

    def test_static_map_gang_trains(self):
        w0, gtab = self._tables()
        out, servers, clients, ctl = self._run(w0, gtab, 6)
        want = w0 + gtab.sum(axis=(0, 1))
        np.testing.assert_allclose(out, want, rtol=1e-5)
        assert [s.owned_shards for s in servers] == [[0], [1]]

    def test_live_migration_is_bitwise_transparent(self):
        """One mid-run migration: final params bitwise-equal to the
        static run; the drain went through the NACK path."""
        w0, gtab = self._tables()
        static, *_ = self._run(w0, gtab, 6)

        def hook(r, ctl, servers, threads):
            if r == 3:
                assert ctl.migrate(1, 0)

        migrated, servers, clients, ctl = self._run(w0, gtab, 6, hook=hook)
        np.testing.assert_array_equal(static, migrated)
        assert servers[0].owned_shards == [0, 1]
        assert servers[1].owned_shards == []
        assert sum(int(c._m_nacks.value) for c in clients) > 0, \
            "nobody drained through NACK_MAP — the migration was free?"

    def test_live_migration_under_drop_dup_plans_stays_bitwise(self):
        """The acceptance matrix, shardctl edition: client data drops +
        dups, server reply drops, a migration mid-run — still bitwise."""
        w0, gtab = self._tables()
        static, *_ = self._run(w0, gtab, 6)

        def hook(r, ctl, servers, threads):
            if r == 2:
                assert ctl.migrate(0, 1)

        client_plans = {
            i: FaultPlan(seed=i, drop_every=3, dup_every=4, tags=DATA_TAGS)
            for i in range(2)
        }
        server_plan = FaultPlan(seed=9, drop_every=3, tags=REPLY_TAGS)
        faulty, servers, clients, ctl = self._run(
            w0, gtab, 6, hook=hook,
            client_plans=client_plans, server_plan=server_plan)
        np.testing.assert_array_equal(static, faulty)
        assert sum(int(s.dup_ops) for s in servers) > 0, \
            "no duplicate was ever admitted — the plan never bit"

    def test_migration_preserves_int8_error_feedback(self):
        """Quantized gang: the residual telescope survives a migration
        (encode-once staging + migrated dedup keep the applied stream
        identical), so final params match the static int8 run bitwise."""
        w0, gtab = self._tables(size=4096)

        def hook(r, ctl, servers, threads):
            if r == 3:
                assert ctl.migrate(1, 0)

        static, *_ = self._run(w0, gtab, 6, codec="int8")
        migrated, _, clients, _ = self._run(w0, gtab, 6, codec="int8",
                                            hook=hook)
        np.testing.assert_array_equal(static, migrated)
        assert any(c.residual_norm() > 0 for c in clients)

    def test_lease_expiry_failover_is_bitwise_transparent(self, tmp_path):
        """The dead-server path end-to-end: beats stop, the controller's
        lease on the server expires (fake clock), failover ADOPTs the
        shard from its checkpoint on a survivor, clients re-route via
        the broadcast map — final params bitwise vs the static run,
        under drop/dup plans."""
        w0, gtab = self._tables()
        static, *_ = self._run(w0, gtab, 6)

        now = [0.0]
        killed = []

        def hook(r, ctl, servers, threads):
            now[0] += 1.0
            if r == 3:
                import time as _time

                # The controller's lease on server 1 must be armed by a
                # real beat before the death is observable as expiry.
                t0 = _time.monotonic()
                while ctl.leases._expiry.get(1) is None:
                    ctl.pump()
                    assert _time.monotonic() - t0 < 10, "no beat arrived"
                    _time.sleep(0.01)
                # Quiesced turn boundary: checkpoint, kill, expire.
                servers[1].save_state(str(tmp_path))
                servers[1].live.stop()
                threads[1].join(10)
                assert not threads[1].is_alive()
                killed.append(1)
                ctl._drain_beats()  # the dead server's last beats
                now[0] += 100.0
                # Let the live server's next beat renew under the jumped
                # clock, so only the dead server's lease reads expired.
                t0 = _time.monotonic()
                while ctl.leases._expiry.get(0) is not None \
                        and ctl.leases._expiry[0] < now[0]:
                    ctl._drain_beats()
                    assert _time.monotonic() - t0 < 10, "no fresh beat"
                    _time.sleep(0.01)
                ctl.check_leases()
                assert ctl.smap.owner(1) == 0, "failover did not move shard"

        client_plans = {
            i: FaultPlan(seed=i, drop_every=4, dup_every=5, tags=DATA_TAGS)
            for i in range(2)
        }
        failed, servers, clients, ctl = self._run(
            w0, gtab, 6, hook=hook, ckpt_dir=str(tmp_path),
            client_plans=client_plans,
            ctl_kwargs=dict(lease_ttl_s=5.0, clock=lambda: now[0]))
        np.testing.assert_array_equal(static, failed)
        assert killed == [1]
        assert servers[0].owned_shards == [0, 1]
        # Every client adopted the failover map (the broadcast is polled
        # between rounds, so the re-route may be proactive rather than a
        # mid-op NACK/timeout re-route — either path must land on v1).
        assert all(c.smap.version == 1 for c in clients)


# ---------------------------------------------------------------------------
# controller plumbing


class TestController:
    def test_beats_feed_leases_and_window(self):
        servers, clients, threads, ctl = launch_sc(
            2, 1, 32, client_ft=FTConfig(op_deadline_s=0.3, max_retries=6,
                                         heartbeat_s=0.02,
                                         backoff_base_s=0.005,
                                         backoff_cap_s=0.02),
            server_ft=FTConfig(op_deadline_s=0.3, max_retries=6,
                               heartbeat_s=0.02, backoff_base_s=0.005,
                               backoff_cap_s=0.02))
        w0 = np.arange(32, dtype=np.float32)
        start_clients(clients, w0)
        deadline = 5.0
        import time as _time
        t0 = _time.monotonic()
        while int(ctl._m_beats.value) == 0:
            ctl.pump()
            assert _time.monotonic() - t0 < deadline, "no beat ever arrived"
            _time.sleep(0.01)
        out = finish(clients, threads, ctl)
        np.testing.assert_array_equal(out, w0)

    def test_policy_driven_rebalance_moves_the_hot_shard(self):
        """Synthetic window: feed the controller a skewed load report
        and let maybe_rebalance execute a real migration."""
        now = [0.0]
        servers, clients, threads, ctl = launch_sc(
            2, 2, 48,
            ctl_kwargs=dict(policy=RebalancePolicy(ratio=2.0,
                                                   min_busy_s=0.0,
                                                   cooldown_s=1.0),
                            clock=lambda: now[0]))
        w0 = np.arange(48, dtype=np.float32)
        start_clients(clients, w0)
        ctl.pump()
        ctl._window = {0: {0: ShardLoad(ops=50, busy_s=2.0)},
                       1: {1: ShardLoad(ops=50, busy_s=0.1)}}
        now[0] += 10.0
        assert ctl.maybe_rebalance()
        assert ctl.smap.owner(0) == 1
        gtab = np.ones((2, 2, 48), np.float32)
        lockstep(clients, gtab, 2)
        out = finish(clients, threads, ctl)
        np.testing.assert_allclose(out, w0 + 4.0, rtol=1e-6)
        assert servers[1].owned_shards == [0, 1]

    def test_migrate_refuses_noops(self):
        servers, clients, threads, ctl = launch_sc(2, 1, 32)
        w0 = np.arange(32, dtype=np.float32)
        start_clients(clients, w0)
        ctl.pump()
        assert not ctl.migrate(0, 0)  # already there
        assert not ctl.migrate(99, 1)  # unknown shard
        out = finish(clients, threads, ctl)
        np.testing.assert_array_equal(out, w0)


# ---------------------------------------------------------------------------
# guards


class TestGuards:
    def test_shardctl_without_deadlines_is_rejected(self):
        router = LocalRouter(2)
        with pytest.raises(ValueError, match="op_deadline_s"):
            ParamClient(1, [0], router.endpoint(1), shardctl=True,
                        ft=FTConfig())

    def test_mixed_legacy_and_shardctl_inits_fail_loudly(self):
        """One v4 and one legacy client on a server must not negotiate."""
        from mpit_tpu.aio import TaskError

        router = LocalRouter(3)
        server = ParamServer(0, [1, 2], router.endpoint(0), ft=FAST_FT)
        err = []

        def run_server():
            try:
                server.start()
            except TaskError as exc:
                err.append(exc)

        th = threading.Thread(target=run_server, daemon=True)
        th.start()
        sc_client = ParamClient(1, [0], router.endpoint(1), ft=FAST_FT,
                                shardctl=True)
        legacy = ParamClient(2, [0], router.endpoint(2), ft=FAST_FT)
        w = np.ones(8, np.float32)

        def start_bg(c):
            t = threading.Thread(
                target=lambda: c.start(w.copy(), np.zeros_like(w)),
                daemon=True)
            t.start()
            return t

        t1 = start_bg(sc_client)
        t2 = start_bg(legacy)
        th.join(10)
        assert err, "server accepted a mixed v4/legacy gang"
        server.live.stop()
        sc_client.live.stop()
        legacy.live.stop()
        for t in (t1, t2):
            t.join(5)
