"""Hierarchical quantized aggregation (docs/PROTOCOL.md §13).

The contract under test: pre-reducing colocated gradients on the group
plane and reducing across the REDUCE tree changes *who sends what where*
and nothing else — the value the server applies is bitwise the fixed-
order fold of the gang's gradients (per-hop codec round-trips included),
whatever the arrival order, tree shape, or chunk-level fault pattern.
Stragglers re-route loudly (LATE -> direct push), never silently and
never as a hang.

The oracle below replays the plan's fold in plain numpy — same codec
code, same fixed order — and a flat control gang pushes the oracle's
values; the hierarchical gang's final params must equal the control's
bitwise.
"""

import threading
import time

import numpy as np
import pytest

from mpit_tpu.agg import (
    AggClient,
    AggConfig,
    ReductionPlan,
    pack_reduce_header,
    reduce_ack_frame,
    unpack_reduce_header,
)
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import (
    FaultPlan,
    FaultyTransport,
    FTConfig,
    RetryExhausted,
    chunk_elems_for,
)
from mpit_tpu.aio import TaskError
from mpit_tpu.ps import ParamClient, ParamServer, tags

REDUCE_TAGS = frozenset({tags.REDUCE})
REDUCE_ACK_TAGS = frozenset({tags.REDUCE_ACK})

_ns_counter = [0]


def agg_ft(deadline=2.0, retries=10, chunk_bytes=0):
    return FTConfig(op_deadline_s=deadline, max_retries=retries,
                    backoff_base_s=0.005, backoff_cap_s=0.02,
                    chunk_bytes=chunk_bytes)


def join_all(threads, timeout=90):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# plan units


class TestReductionPlan:
    def test_singleton_groups_and_reps(self):
        plan = ReductionPlan.build([2, 3, 4, 5])
        assert all(plan.is_rep(r) for r in [2, 3, 4, 5])
        assert plan.root in [2, 3, 4, 5]
        # every non-root rep has a parent; edges are acyclic and reach
        # the root
        for r in [2, 3, 4, 5]:
            hops, node = 0, r
            while plan.parent(node) is not None:
                node = plan.parent(node)
                hops += 1
                assert hops <= 4
            assert node == plan.root

    def test_groups_elect_min_rank(self):
        plan = ReductionPlan.build([2, 3, 4, 5], groups=[(3, 2), (5, 4)])
        assert plan.rep(2) == 2 and plan.rep(3) == 2
        assert plan.rep(4) == 4 and plan.rep(5) == 4
        assert plan.members(2) == [3]
        assert not plan.is_rep(3)
        assert plan.group_size(5) == 2

    def test_deterministic_and_seed_sensitive(self):
        a = ReductionPlan.build(range(8), fanin=2, seed=1)
        b = ReductionPlan.build(range(8), fanin=2, seed=1)
        assert a.parent_of == b.parent_of
        shapes = {tuple(sorted(ReductionPlan.build(
            range(8), fanin=2, seed=s).parent_of.items()))
            for s in range(6)}
        assert len(shapes) > 1  # seeds actually vary the tree

    def test_subtree_leaves_counts_everyone(self):
        plan = ReductionPlan.build(range(6), groups=[(0, 1, 2)], fanin=2)
        assert plan.subtree_leaves(plan.root) == 6

    def test_overlapping_groups_rejected(self):
        with pytest.raises(ValueError, match="two groups"):
            ReductionPlan.build(range(4), groups=[(0, 1), (1, 2)])

    def test_unknown_rank_rejected(self):
        with pytest.raises(ValueError, match="non-client"):
            ReductionPlan.build([0, 1], groups=[(0, 7)])

    def test_fanin_shapes(self):
        plan = ReductionPlan.build(range(9), fanin=8, seed=0)
        # fanin 8 over 9 reps: the root takes all 8 others
        assert len(plan.children(plan.root)) == 8


# ---------------------------------------------------------------------------
# wire units


class TestReduceWire:
    def test_header_roundtrip(self):
        buf = np.zeros(64, np.uint8)
        pack_reduce_header(buf, 3, 7, 2, 5, 11)
        assert unpack_reduce_header(buf) == (3, 7, 2, 5, 11)

    def test_ack_frame(self):
        frame = reduce_ack_frame(1, 2, 3, 1)
        assert frame.dtype == np.int64
        assert list(frame) == [1, 2, 3, 1]


# ---------------------------------------------------------------------------
# the gang harness: per-client driver threads, lockstep rounds


def launch_agg(nservers, nclients, ft, cfg, client_plans=None,
               server_plan=None, rule="add", codec=None):
    n = nservers + nclients
    router = LocalRouter(n)
    sranks = list(range(nservers))
    cranks = list(range(nservers, n))
    _ns_counter[0] += 1
    namespace = f"test{_ns_counter[0]}"
    servers, threads = [], []
    for r in sranks:
        ep = router.endpoint(r)
        if server_plan is not None:
            ep = FaultyTransport(ep, server_plan)
        servers.append(ParamServer(r, cranks, ep, rule=rule,
                                   ft=FTConfig(rejoin=True)))
        threads.append(threading.Thread(target=servers[-1].start,
                                        daemon=True))
    for t in threads:
        t.start()
    clients = []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        inner = ParamClient(r, sranks, ep, seed_servers=(r == cranks[0]),
                            codec=codec, ft=ft)
        clients.append(AggClient(inner, cranks, cfg, namespace=namespace))
    return servers, clients, threads


class PingBarrier:
    """A lockstep barrier whose waiters keep pumping their client's
    I/O: an idle tree parent must still answer a straggler's retries
    (LATE acks), exactly as a real training loop's ping cadence does."""

    def __init__(self, n):
        self.n = n
        self._count = 0
        self._gen = 0
        self._aborted = False
        self._lock = threading.Lock()

    def abort(self):
        self._aborted = True

    def wait(self, ping=None, timeout=90.0):
        with self._lock:
            gen = self._gen
            self._count += 1
            if self._count == self.n:
                self._count = 0
                self._gen += 1
                return
        bound = time.monotonic() + timeout
        while True:
            with self._lock:
                if self._gen != gen:
                    return
            if self._aborted:
                raise RuntimeError("agg barrier aborted (sibling failed)")
            if ping is not None:
                ping()
            time.sleep(0.001)
            if time.monotonic() > bound:
                self._aborted = True
                raise RuntimeError("agg barrier timed out")


def run_agg_gang(nservers, nclients, ft, cfg, rounds=3, size=8192,
                 client_plans=None, server_plan=None, rule="add",
                 codec=None, seed=42, gtab=None, delays=None,
                 w0=None, round_timeout=90):
    """Seed, run lockstep rounds from per-client driver threads (the
    tree needs every client pumping concurrently), read back client 0's
    params.  ``delays[(client_idx, round)]`` sleeps that client before
    its send — the straggler injection.  Returns (params, stats)."""
    rng = np.random.default_rng(seed)
    drawn = rng.normal(size=size).astype(np.float32)
    if w0 is None:
        w0 = drawn
    if gtab is None:
        gtab = rng.normal(size=(nclients, max(rounds, 1), size)).astype(
            np.float32)
    servers, clients, threads = launch_agg(
        nservers, nclients, ft, cfg, client_plans=client_plans,
        server_plan=server_plan, rule=rule, codec=codec)
    barrier = PingBarrier(nclients)
    errors = {}
    params = []
    for i in range(nclients):
        p = w0.copy() if i == 0 else np.zeros(size, np.float32)
        params.append((p, np.zeros(size, np.float32)))

    def drive(i, c):
        try:
            c.start(*params[i])
            barrier.wait(ping=c.ping)
            for r in range(rounds):
                params[i][1][:] = gtab[i, r]
                if delays:
                    time.sleep(delays.get((i, r), 0.0))
                c.async_send_grad()
                c.wait()
                barrier.wait(ping=c.ping)
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            errors[i] = exc
            barrier.abort()

    drivers = [threading.Thread(target=drive, args=(i, c), daemon=True)
               for i, c in enumerate(clients)]
    for t in drivers:
        t.start()
    deadline = time.monotonic() + round_timeout
    for t in drivers:
        t.join(max(deadline - time.monotonic(), 1.0))
        assert not t.is_alive(), "agg driver hung (never-hang broken)"
    try:
        if errors:
            raise errors[min(errors)]
        clients[0].async_recv_param()
        clients[0].wait()
        stats = {
            "applied": sum(s.grads_applied for s in servers),
            "dups": sum(s.dup_ops for s in servers),
            "retries": sum(c.retries for c in clients),
            "late": sum(
                int(c._m_late.value) for c in clients),
            "fallbacks": sum(
                int(c._m_fallbacks.value) for c in clients),
        }
        return params[0][0].copy(), stats
    finally:
        for c in clients:
            try:
                c.stop()
            except Exception:
                pass
        for s in servers:
            s.live.stop()
        join_all(threads)


# ---------------------------------------------------------------------------
# the numpy oracle: the plan's fixed-order fold, codec hops included


def oracle_pushes(plan, gtab, codec_name, rounds, size):
    """Per round, the value the root pushes upstream: group folds in
    ascending rank order, child subtrees folded in ascending child
    order, every tree hop round-tripped through the codec with the
    sender-held error-feedback residual."""
    codec = codec_mod.get(codec_name)
    cranks = plan.cranks
    idx = {r: i for i, r in enumerate(cranks)}
    residuals = {r: np.zeros(size, np.float32) for r in cranks}

    def fold(rank, r):
        acc = gtab[idx[rank], r].astype(np.float32).copy()
        for m in plan.members(rank):
            acc += gtab[idx[m], r]
        for c in plan.children(rank):
            sub = fold(c, r)
            wire = np.zeros(codec.wire_nbytes(size), np.uint8)
            codec.encode_into(
                sub, wire,
                residual=residuals[c] if codec.uses_residual else None)
            dec = np.zeros(size, np.float32)
            codec.decode_into(wire, dec)
            acc += dec
        return acc

    return [fold(plan.root, r) for r in range(rounds)]


def run_flat_control(nservers, pushes, ft, size, rule="add", codec=None,
                     seed=42):
    """A 1-client flat gang pushing the oracle's per-round values —
    the 'flat pushes under a fixed reduction order' baseline."""
    rng = np.random.default_rng(seed)
    w0 = rng.normal(size=size).astype(np.float32)
    gtab = np.stack([pushes])  # (1, rounds, size)
    return run_agg_gang(nservers, 1, ft, AggConfig(mode="off"),
                        rounds=len(pushes), size=size, rule=rule,
                        codec=codec, seed=seed, gtab=gtab)


# ---------------------------------------------------------------------------
# bitwise parity: hierarchical == flat pushes of the fixed-order fold


class TestHierarchicalBitwise:
    @pytest.mark.parametrize("codec_name", ["none", "bf16", "int8"])
    def test_tree_equals_flat_fold(self, codec_name):
        """4 singleton clients over a binary tree: the root's pushes —
        per-hop codec round-trips included — land bitwise-identical to
        a flat client pushing the oracle fold."""
        size = 8192
        cfg = AggConfig(mode="tree", fanin=2, tree_seed=3,
                        deadline_s=30.0)
        plan = ReductionPlan.build(range(2, 6), fanin=2, seed=3)
        rng = np.random.default_rng(42)
        rng.normal(size=size)  # skip w0 draw: gtab must match run's
        gtab = rng.normal(size=(4, 3, size)).astype(np.float32)
        hier, st = run_agg_gang(2, 4, agg_ft(), cfg, rounds=3, size=size,
                                codec=codec_name, gtab=gtab)
        pushes = oracle_pushes(plan, gtab, codec_name, 3, size)
        flat, _ = run_flat_control(2, pushes, agg_ft(), size,
                                   codec=codec_name)
        np.testing.assert_array_equal(hier, flat)
        assert st["applied"] == 3 * 2  # one GRAD per round per server
        assert st["late"] == 0 and st["fallbacks"] == 0

    def test_prereduce_group_equals_flat_sum(self):
        """One colocated group of 3: the representative pushes the
        on-device group fold; servers see exactly one GRAD per round."""
        size = 6144
        cfg = AggConfig(mode="prereduce", groups=((2, 3, 4),),
                        deadline_s=30.0)
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        gtab = rng.normal(size=(3, 2, size)).astype(np.float32)
        hier, st = run_agg_gang(2, 3, agg_ft(), cfg, rounds=2, size=size,
                                gtab=gtab)
        plan = ReductionPlan.build(range(2, 5), groups=[(2, 3, 4)])
        pushes = oracle_pushes(plan, gtab, "none", 2, size)
        flat, _ = run_flat_control(2, pushes, agg_ft(), size)
        np.testing.assert_array_equal(hier, flat)
        assert st["applied"] == 2 * 2

    def test_tree_with_groups_and_stateful_rule(self):
        """2 groups + a tree over their reps, rmsprop server rule —
        the fold value is what reaches the rule, bitwise."""
        size = 6144
        groups = ((2, 3), (4, 5))
        cfg = AggConfig(mode="tree", groups=groups, fanin=2,
                        tree_seed=1, deadline_s=30.0)
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        gtab = rng.normal(size=(4, 3, size)).astype(np.float32)
        hier, _ = run_agg_gang(2, 4, agg_ft(), cfg, rounds=3, size=size,
                               rule="rmsprop", codec="int8", gtab=gtab)
        plan = ReductionPlan.build(range(2, 6), groups=groups, fanin=2,
                                   seed=1)
        pushes = oracle_pushes(plan, gtab, "int8", 3, size)
        flat, _ = run_flat_control(2, pushes, agg_ft(), size,
                                   rule="rmsprop", codec="int8")
        np.testing.assert_array_equal(hier, flat)

    def test_chunked_upstream_push_composes(self):
        """FLAG_CHUNKED on the client<->server wire + the REDUCE tree:
        chunking never changes bytes, so the fold still matches the
        unchunked control bitwise."""
        size = 8192
        cfg = AggConfig(mode="tree", fanin=2, tree_seed=0,
                        deadline_s=30.0, chunk_bytes=8192)
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        gtab = rng.normal(size=(3, 2, size)).astype(np.float32)
        hier, _ = run_agg_gang(1, 3, agg_ft(chunk_bytes=8192), cfg,
                               rounds=2, size=size, gtab=gtab)
        plan = ReductionPlan.build(range(1, 4), fanin=2, seed=0)
        pushes = oracle_pushes(plan, gtab, "none", 2, size)
        flat, _ = run_flat_control(1, pushes, agg_ft(), size)
        np.testing.assert_array_equal(hier, flat)

    def test_off_mode_is_flat_passthrough(self):
        size = 4096
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        gtab = rng.normal(size=(2, 2, size)).astype(np.float32)
        flat_raw, _ = run_agg_gang(1, 2, agg_ft(), AggConfig(mode="off"),
                                   rounds=2, size=size, gtab=gtab)
        # flat: both clients push their own grads (2 applies per round)
        assert flat_raw is not None


# ---------------------------------------------------------------------------
# straggler handling: loud, counted, re-routed, never lost, never a hang


class TestStragglers:
    def test_late_member_falls_back_to_direct_push(self):
        """A colocated member sleeping past the deadline: the rep folds
        without it, the member direct-pushes.  Integer grads make float
        addition exact, so the final params still carry every
        contribution regardless of apply order."""
        size = 4096
        cfg = AggConfig(mode="prereduce", groups=((1, 2),),
                        deadline_s=0.4)
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        w0 = rng.integers(-64, 65, size=size).astype(np.float32)
        gtab = rng.integers(-8, 9, size=(2, 2, size)).astype(np.float32)
        final, st = run_agg_gang(
            1, 2, agg_ft(), cfg, rounds=2, size=size, gtab=gtab, w0=w0,
            delays={(1, 0): 1.2})
        expect = w0 + gtab.sum(axis=(0, 1))
        np.testing.assert_array_equal(final, expect)
        assert st["late"] >= 1, "the exclusion was never counted"
        assert st["fallbacks"] >= 1, "the member never re-routed"

    def test_late_tree_child_falls_back(self):
        """A tree leaf sleeping past the deadline: its parent folds
        without it (LATE acks), the leaf direct-pushes its partial."""
        size = 4096
        cfg = AggConfig(mode="tree", fanin=2, tree_seed=0,
                        deadline_s=0.4)
        plan = ReductionPlan.build(range(1, 4), fanin=2, seed=0)
        # pick a non-root leaf to straggle
        leaf = next(r for r in plan.cranks
                    if plan.parent(r) is not None and not plan.children(r))
        leaf_idx = plan.cranks.index(leaf)
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        w0 = rng.integers(-64, 65, size=size).astype(np.float32)
        gtab = rng.integers(-8, 9, size=(3, 2, size)).astype(np.float32)
        final, st = run_agg_gang(
            1, 3, agg_ft(), cfg, rounds=2, size=size, gtab=gtab, w0=w0,
            delays={(leaf_idx, 0): 1.5})
        expect = w0 + gtab.sum(axis=(0, 1))
        np.testing.assert_array_equal(final, expect)
        assert st["late"] >= 1
        assert st["fallbacks"] >= 1


# ---------------------------------------------------------------------------
# faults on the REDUCE hops: retries recover, bitwise holds


class TestReduceFaults:
    def test_drop_dup_on_reduce_hops_bitwise(self):
        """Every 3rd REDUCE chunk dropped + every 4th duplicated on
        every client, every 5th ack dropped: the resend/dedup
        discipline recovers and the fold stays bitwise — a generous
        straggler deadline keeps faults from masquerading as
        stragglers."""
        size = 8192
        cfg = AggConfig(mode="tree", fanin=2, tree_seed=2,
                        deadline_s=30.0)
        rng = np.random.default_rng(42)
        rng.normal(size=size)
        gtab = rng.normal(size=(4, 2, size)).astype(np.float32)
        plans = {
            i: FaultPlan(seed=5 + i, drop_every=3, dup_every=4,
                         tags=REDUCE_TAGS | REDUCE_ACK_TAGS)
            for i in range(4)
        }
        hier, st = run_agg_gang(2, 4, agg_ft(deadline=0.3), cfg,
                                rounds=2, size=size, gtab=gtab,
                                client_plans=plans)
        plan = ReductionPlan.build(range(2, 6), fanin=2, seed=2)
        pushes = oracle_pushes(plan, gtab, "none", 2, size)
        flat, _ = run_flat_control(2, pushes, agg_ft(), size)
        np.testing.assert_array_equal(hier, flat)
        assert st["late"] == 0 and st["fallbacks"] == 0


# ---------------------------------------------------------------------------
# the §13 property test (ISSUE 14 satellite): seeds x tree shapes x plans


@pytest.mark.parametrize("seed", range(5))
def test_property_reduce_faults_bitwise_or_loud(seed):
    """≥5 seeds × random tree shapes × random {drop, dup, delay} plans
    on the REDUCE hops: the gang either completes with final params
    bitwise-equal to the flat fixed-order-fold control — int8 EF hops
    included — or fails loudly.  Never a hang: drivers run under a hard
    timeout inside run_agg_gang."""
    rng = np.random.default_rng(seed)
    nclients = int(rng.integers(3, 6))
    fanin = int(rng.choice([1, 2, 3]))
    tree_seed = int(rng.integers(0, 100))
    codec_name = str(rng.choice(["none", "int8"]))
    size = int(rng.choice([6144, 8192]))
    rounds = 2
    cfg = AggConfig(mode="tree", fanin=fanin, tree_seed=tree_seed,
                    deadline_s=30.0)
    grng = np.random.default_rng(42)
    grng.normal(size=size)
    gtab = grng.normal(size=(nclients, rounds, size)).astype(np.float32)
    plans = {
        i: FaultPlan(seed=seed * 17 + i, drop_rate=0.10, dup_rate=0.08,
                     delay_rate=0.15, delay_polls=4,
                     tags=REDUCE_TAGS | REDUCE_ACK_TAGS)
        for i in range(nclients)
    }
    try:
        hier, st = run_agg_gang(
            2, nclients, agg_ft(deadline=0.3, retries=8), cfg,
            rounds=rounds, size=size, gtab=gtab, client_plans=plans,
            codec=codec_name, round_timeout=120)
    except (TaskError, RetryExhausted, AssertionError):
        return  # loud is an acceptable outcome; a hang is not
    plan = ReductionPlan.build(range(2, 2 + nclients), fanin=fanin,
                               seed=tree_seed)
    pushes = oracle_pushes(plan, gtab, codec_name, rounds, size)
    flat, _ = run_flat_control(2, pushes, agg_ft(), size,
                               codec=codec_name)
    if st["fallbacks"] == 0 and st["late"] == 0:
        np.testing.assert_array_equal(hier, flat)


# ---------------------------------------------------------------------------
# launcher wiring (--agg)


class TestLaunchWiring:
    def test_parse_agg_groups(self):
        from mpit_tpu.train.launch import parse_agg_groups

        assert parse_agg_groups("") == ()
        assert parse_agg_groups("4,5;6,7") == ((4, 5), (6, 7))
        assert parse_agg_groups(" 2 , 3 ; 9 ") == ((2, 3), (9,))

    def test_agg_requires_framed_wire(self):
        inner = ParamClient(1, [0], LocalRouter(2).endpoint(1))
        with pytest.raises(ValueError, match="op_deadline_s"):
            AggClient(inner, [1], AggConfig(mode="tree"))

    def test_agg_rejects_shardctl(self):
        inner = ParamClient(1, [0], LocalRouter(2).endpoint(1),
                            shardctl=True,
                            ft=FTConfig(op_deadline_s=1.0))
        with pytest.raises(ValueError, match="shard map"):
            AggClient(inner, [1], AggConfig(mode="prereduce"))

    def test_off_mode_needs_no_ft(self):
        inner = ParamClient(1, [0], LocalRouter(2).endpoint(1))
        agg = AggClient(inner, [1], AggConfig(mode="off"))
        assert agg.plan is None  # strict passthrough
