"""Worker-pool data-plane tests (comm/pool.py + native mt_pool_*).

Two suites for the ISSUE 17 seam:

* **Pooled-vs-serial bitwise parity.**  Every kernel the pool runs
  (codec encode/decode, XOR, f32 fold, chunk gather/scatter) must
  produce bytes identical to the serial fallback — per codec, per chunk
  geometry (BLOCK-aligned and tailed shards), per thread count, across
  seeds.  This is the determinism contract the module docstring pins:
  completion order never influences bytes, and ``MPIT_POOL_THREADS=0``
  is the same bytes, not a different path.  Includes int8
  error-feedback residual exactness under a chunk retry (re-encode from
  the pre-encode residual snapshot must reproduce the identical frame).

* **Lifecycle.**  ``close()`` drains queued jobs before the workers
  exit, any submit after close raises :class:`PoolClosedError` loudly
  (serial pools included), and 32 open/close cycles leak no OS thread.

The parity suite needs the compiled library; without a toolchain it
skips (the serial fallback is then the only path, and tier-1 stays
green by construction).
"""

import numpy as np
import pytest

from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm import pool as pool_mod

HAVE_NATIVE = pool_mod._load_native() is not None

pooled = pytest.mark.skipif(
    not HAVE_NATIVE,
    reason="native pool library unavailable (serial fallback only)")

BLOCK = codec_mod.BLOCK
#: one BLOCK-aligned shard, one tailed (size % BLOCK != 0) shard
SIZES = [3 * BLOCK, 5 * BLOCK + 137]
SEEDS = range(5)
CODEC_NAMES = ["none", "bf16", "int8"]


def rnd(n, seed, scale=3.0):
    return (scale * np.random.default_rng(seed).standard_normal(n)).astype(
        np.float32)


def chunk_bounds(size):
    """One interior BLOCK-aligned chunk plus the (possibly tailed)
    trailing chunk — the §12 chunk geometry int8 frames require."""
    mid = max(BLOCK, (size // (2 * BLOCK)) * BLOCK)
    return [(0, mid), (mid, size)]


def _encode_chunks(pool, codec, x, residual):
    """Encode every chunk of ``x`` through ``pool``, collecting in
    submission order; returns the per-chunk wire frames."""
    wires = []
    jobs = []
    for lo, hi in chunk_bounds(x.size):
        wire = np.zeros(codec.wire_nbytes(hi - lo), np.uint8)
        res = residual[lo:hi] if residual is not None else None
        jobs.append(pool.submit_encode(codec, x[lo:hi], wire, res))
        wires.append(wire)
    for j in jobs:
        j.result()
    return wires


@pooled
@pytest.mark.parametrize("threads", [1, 2, 4])
class TestPooledSerialParity:
    """Bitwise equality: pooled kernels vs the serial fallback."""

    def test_codec_chunk_roundtrip_bitwise(self, threads):
        pool = pool_mod.WorkerPool(threads)
        serial = pool_mod.WorkerPool(0)
        try:
            assert not pool.serial and pool.threads == threads
            assert serial.serial
            for seed in SEEDS:
                for name in CODEC_NAMES:
                    codec = codec_mod.get(name)
                    for size in SIZES:
                        x = rnd(size, seed)
                        res_p = (np.zeros(size, np.float32)
                                 if codec.uses_residual else None)
                        res_s = (np.zeros(size, np.float32)
                                 if codec.uses_residual else None)
                        wp = _encode_chunks(pool, codec, x, res_p)
                        ws = _encode_chunks(serial, codec, x, res_s)
                        for a, b in zip(wp, ws):
                            assert a.tobytes() == b.tobytes(), (
                                seed, name, size)
                        if codec.uses_residual:
                            assert np.array_equal(res_p, res_s)
                        # decode the serial frames back through both
                        out_p = np.zeros(size, np.float32)
                        out_s = np.zeros(size, np.float32)
                        jobs = []
                        for (lo, hi), w in zip(chunk_bounds(size), ws):
                            jobs.append(pool.submit_decode(
                                codec, w, out_p[lo:hi]))
                            serial.submit_decode(
                                codec, w, out_s[lo:hi]).result()
                        for j in jobs:
                            j.result()
                        assert out_p.tobytes() == out_s.tobytes(), (
                            seed, name, size)
        finally:
            pool.close()
            serial.close()

    def test_xor_and_fold_bitwise(self, threads):
        pool = pool_mod.WorkerPool(threads)
        serial = pool_mod.WorkerPool(0)
        try:
            for seed in SEEDS:
                rng = np.random.default_rng(seed)
                n = int(rng.integers(BLOCK, 4 * BLOCK))
                a = rng.integers(0, 256, n).astype(np.uint8)
                b = rng.integers(0, 256, n).astype(np.uint8)
                out_p = np.empty(n, np.uint8)
                out_s = np.empty(n, np.uint8)
                pool.submit_xor(a, b, out_p).result()
                serial.submit_xor(a, b, out_s).result()
                assert out_p.tobytes() == out_s.tobytes()
                assert out_s.tobytes() == np.bitwise_xor(a, b).tobytes()

                own = rnd(n, seed)
                children = [rnd(n, seed * 7 + k + 1) for k in range(3)]
                f_p = np.empty(n, np.float32)
                f_s = np.empty(n, np.float32)
                pool.submit_fold_f32(own, children, f_p).result()
                serial.submit_fold_f32(own, children, f_s).result()
                assert f_p.tobytes() == f_s.tobytes()
        finally:
            pool.close()
            serial.close()

    def test_gather_scatter_bitwise(self, threads):
        pool = pool_mod.WorkerPool(threads)
        serial = pool_mod.WorkerPool(0)
        try:
            for seed in SEEDS:
                for name in CODEC_NAMES:
                    codec = codec_mod.get(name)
                    for size in SIZES:
                        full = np.zeros(codec.wire_nbytes(size), np.uint8)
                        serial.submit_encode(
                            codec, rnd(size, seed), full,
                            np.zeros(size, np.float32)
                            if codec.uses_residual else None).result()
                        for lo, hi in chunk_bounds(size):
                            nb = codec.wire_nbytes(hi - lo)
                            c_p = np.zeros(nb, np.uint8)
                            c_s = np.zeros(nb, np.uint8)
                            pool.submit_gather(
                                codec, full, size, lo, hi, c_p).result()
                            serial.submit_gather(
                                codec, full, size, lo, hi, c_s).result()
                            assert c_p.tobytes() == c_s.tobytes()
                            f_p = np.zeros_like(full)
                            f_s = np.zeros_like(full)
                            pool.submit_scatter(
                                codec, f_p, size, lo, hi, c_s).result()
                            serial.submit_scatter(
                                codec, f_s, size, lo, hi, c_s).result()
                            assert f_p.tobytes() == f_s.tobytes()
        finally:
            pool.close()
            serial.close()

    def test_int8_residual_exact_under_chunk_retry(self, threads):
        """A chunk retry re-encodes from the pre-encode residual
        snapshot (the §12.4 retry rule): the retried frame and the
        post-encode residual must be bit-identical to the first
        attempt's, pooled and serial alike."""
        pool = pool_mod.WorkerPool(threads)
        serial = pool_mod.WorkerPool(0)
        codec = codec_mod.get("int8")
        try:
            for seed in SEEDS:
                size = 5 * BLOCK + 137
                x = rnd(size, seed)
                res0 = rnd(size, seed + 100, scale=0.01)  # warm EF state
                for lo, hi in chunk_bounds(size):
                    nb = codec.wire_nbytes(hi - lo)
                    frames, residuals = [], []
                    for p in (pool, serial):
                        for _attempt in range(2):  # original + retry
                            res = res0.copy()
                            wire = np.zeros(nb, np.uint8)
                            p.submit_encode(
                                codec, x[lo:hi], wire,
                                res[lo:hi]).result()
                            frames.append(wire.tobytes())
                            residuals.append(res.tobytes())
                    assert len(set(frames)) == 1, (seed, lo, hi)
                    assert len(set(residuals)) == 1, (seed, lo, hi)
        finally:
            pool.close()
            serial.close()


def _os_threads() -> int:
    """This process's OS thread count (native pool workers are pthreads
    invisible to the threading module)."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("Threads:"):
                return int(line.split()[1])
    raise AssertionError("no Threads: line in /proc/self/status")


class TestLifecycle:
    def test_submit_after_close_raises_serial(self):
        pool = pool_mod.WorkerPool(0)
        pool.close()
        with pytest.raises(pool_mod.PoolClosedError):
            pool.submit_xor(np.zeros(8, np.uint8), np.zeros(8, np.uint8),
                            np.zeros(8, np.uint8))

    @pooled
    def test_close_drains_queued_jobs(self):
        pool = pool_mod.WorkerPool(1)
        n = 1 << 20
        a = np.random.default_rng(0).integers(0, 256, n).astype(np.uint8)
        b = np.random.default_rng(1).integers(0, 256, n).astype(np.uint8)
        outs = [np.zeros(n, np.uint8) for _ in range(8)]
        jobs = [pool.submit_xor(a, b, out) for out in outs]
        pool.close()  # must drain, not drop
        expect = np.bitwise_xor(a, b).tobytes()
        for out in outs:
            assert out.tobytes() == expect
        for j in jobs:  # collecting after close is a no-op, not a hang
            j.result()
            assert j.done()
        with pytest.raises(pool_mod.PoolClosedError):
            pool.submit_copy(a, outs[0])

    @pooled
    def test_no_thread_leak_across_open_close_cycles(self):
        a = np.arange(4096, dtype=np.uint8)
        b = a[::-1].copy()
        out = np.empty_like(a)
        # a first cycle warms lazy state (ctypes, obs registry)
        p = pool_mod.WorkerPool(2)
        p.submit_xor(a, b, out).result()
        p.close()
        before = _os_threads()
        for _ in range(32):
            p = pool_mod.WorkerPool(2)
            assert p.threads == 2
            p.submit_xor(a, b, out).result()
            p.close()
            p.close()  # idempotent
        assert _os_threads() == before

    @pooled
    def test_done_polls_without_blocking(self):
        pool = pool_mod.WorkerPool(1)
        try:
            n = 1 << 22
            a = np.zeros(n, np.uint8)
            b = np.ones(n, np.uint8)
            out = np.empty(n, np.uint8)
            job = pool.submit_xor(a, b, out)
            while not job.done():  # scheduler-style poll, no result()
                pass
            assert out.tobytes() == np.bitwise_xor(a, b).tobytes()
        finally:
            pool.close()

    def test_configure_replaces_and_closes_previous(self):
        first = pool_mod.configure(0)
        second = pool_mod.configure(0)
        assert second is pool_mod.get_pool()
        assert second is not first
        with pytest.raises(pool_mod.PoolClosedError):
            first.submit_copy(np.zeros(4, np.uint8), np.zeros(4, np.uint8))
