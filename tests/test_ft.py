"""mpit_tpu.ft — fault-tolerance subsystem tests.

Every recovery path is driven by deterministic fault injection
(ft/faults.py): the FaultyTransport wrapper drops / delays / duplicates /
severs messages on a schedule that is a pure function of
(seed, src, dst, tag, per-channel count), so each failure below is the
same failure on every run.

Topology notes: client-side faults wrap the client's transport (GRAD,
PARAM_REQ, PARAM_PUSH are client sends); ack/snapshot faults wrap the
*server's* transport (GRAD_ACK, PARAM, PARAM_PUSH_ACK are server sends).
Bitwise assertions rely on lockstep turns — each client awaits its acks
before the next client ships — which pins the cross-client apply order;
FIFO channels + at-most-once dedup then make the faulty run's apply
stream identical to the fault-free one.
"""

import threading
import time

import numpy as np
import pytest

from mpit_tpu.aio import (
    DeadlineExceeded,
    Scheduler,
    TaskError,
    aio_recv,
    aio_sleep,
    deadline_at,
)
from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.ft import (
    EVICTED,
    DedupTable,
    FaultPlan,
    FaultyTransport,
    FTConfig,
    LeaseRegistry,
    RetryExhausted,
    RetryPolicy,
)
from mpit_tpu.ps import ParamClient, ParamServer, tags

#: the retried data channels — INIT (the membership rendezvous) and
#: STOP/HEARTBEAT (covered by leases, not retry) stay clean.
DATA_TAGS = frozenset({tags.GRAD, tags.PARAM_REQ, tags.PARAM_PUSH})
REPLY_TAGS = frozenset({tags.GRAD_ACK, tags.PARAM, tags.PARAM_PUSH_ACK})

#: a fast retry posture for LocalRouter-speed tests
FAST_FT = FTConfig(op_deadline_s=0.25, max_retries=8,
                   backoff_base_s=0.005, backoff_cap_s=0.02)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "role thread did not stop (hang)"


# ---------------------------------------------------------------------------
# scheduler timers


class TestSchedulerTimers:
    def test_aio_sleep_elapses(self):
        sched = Scheduler(idle_usec=0)
        t0 = time.monotonic()
        task = sched.spawn(aio_sleep(0.05), name="sleep")
        sched.wait()
        assert task.result is True
        assert time.monotonic() - t0 >= 0.05

    def test_aio_sleep_aborts_on_live_drop(self):
        from mpit_tpu.aio import LiveFlag

        live = LiveFlag()
        sched = Scheduler(idle_usec=0)
        task = sched.spawn(aio_sleep(60.0, live=live), name="sleep")
        live.stop()
        sched.wait()
        assert task.result is False

    def test_recv_deadline_raises(self):
        router = LocalRouter(2)
        sched = Scheduler(idle_usec=0)
        sched.spawn(
            aio_recv(router.endpoint(0), 1, tags.GRAD,
                     deadline=deadline_at(0.03)),
            name="recv",
        )
        with pytest.raises(TaskError) as err:
            sched.wait()
        assert isinstance(err.value.cause, DeadlineExceeded)
        assert err.value.cause.tag == tags.GRAD

    def test_deadline_at_none_passthrough(self):
        assert deadline_at(None) is None
        assert deadline_at(1.0) > time.monotonic()


# ---------------------------------------------------------------------------
# fault plan + transport


class TestFaultPlan:
    def test_parse_roundtrip(self):
        plan = FaultPlan.parse(
            "seed=7,drop_every=3,dup_every=5,delay_every=2,delay_polls=4")
        assert (plan.seed, plan.drop_every, plan.dup_every) == (7, 3, 5)
        assert plan.delay_polls == 4

    def test_parse_unknown_field_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown fault-plan field"):
            FaultPlan.parse("seed=1,frobnicate=2")

    def test_every_k_counts_per_channel(self):
        plan = FaultPlan(drop_every=3)
        verdicts = [plan.decide(0, 1, tags.GRAD, n) for n in range(1, 7)]
        assert verdicts == ["pass", "pass", "drop", "pass", "pass", "drop"]
        # an independent channel has its own count
        assert plan.decide(0, 1, tags.PARAM_REQ, 1) == "pass"

    def test_rate_mode_is_seed_deterministic(self):
        plan_a = FaultPlan(seed=3, drop_rate=0.3, dup_rate=0.3)
        plan_b = FaultPlan(seed=3, drop_rate=0.3, dup_rate=0.3)
        decisions = [plan_a.decide(0, 1, tags.GRAD, n) for n in range(1, 200)]
        assert decisions == [plan_b.decide(0, 1, tags.GRAD, n)
                             for n in range(1, 200)]
        assert "drop" in decisions and "dup" in decisions
        # a different seed gives a different schedule
        other = [FaultPlan(seed=4, drop_rate=0.3, dup_rate=0.3)
                 .decide(0, 1, tags.GRAD, n) for n in range(1, 200)]
        assert decisions != other

    def test_tags_filter(self):
        plan = FaultPlan(drop_every=1, tags=frozenset({tags.GRAD}))
        assert plan.decide(0, 1, tags.GRAD, 1) == "drop"
        assert plan.decide(0, 1, tags.PARAM, 1) == "pass"
        assert plan.decide(0, 1, -5, 1) == "pass"  # internal tags never


class TestFaultyTransport:
    def _pair(self, plan):
        router = LocalRouter(2)
        return FaultyTransport(router.endpoint(0), plan), router.endpoint(1)

    def test_drop_never_delivers(self):
        src, dst = self._pair(FaultPlan(drop_every=1))
        src.send(b"x", 1, tags.GRAD)  # completes for the sender
        assert src.dropped == 1
        assert not dst.iprobe(0, tags.GRAD)

    def test_dup_delivers_twice(self):
        src, dst = self._pair(FaultPlan(dup_every=1))
        src.send(b"x", 1, tags.GRAD)
        assert dst.recv(0, tags.GRAD) == b"x"
        assert dst.recv(0, tags.GRAD) == b"x"
        assert src.duplicated == 1

    def test_delay_defers_post(self):
        src, dst = self._pair(FaultPlan(delay_every=1, delay_polls=5))
        handle = src.isend(b"x", 1, tags.GRAD)
        polls = 0
        while not src.test(handle):
            polls += 1
        assert polls >= 4
        assert dst.recv(0, tags.GRAD) == b"x"

    def test_sever_cuts_the_link(self):
        src, dst = self._pair(FaultPlan())
        src.send(b"a", 1, tags.GRAD)
        src.sever(1)
        src.send(b"b", 1, tags.GRAD)
        assert dst.recv(0, tags.GRAD) == b"a"
        assert not dst.iprobe(0, tags.GRAD)
        assert src.dropped == 1

    def test_recv_side_is_faithful(self):
        src, dst = self._pair(FaultPlan(drop_every=2))
        wrapped_dst = FaultyTransport(dst, FaultPlan(drop_every=2))
        src.send(b"x", 1, tags.GRAD)
        assert wrapped_dst.recv(0, tags.GRAD) == b"x"


# ---------------------------------------------------------------------------
# dedup + leases + retry units


class TestDedupTable:
    def test_fresh_dup_stale(self):
        t = DedupTable()
        assert t.admit(1, tags.GRAD, 0, 1) == "fresh"
        assert t.admit(1, tags.GRAD, 0, 1) == "dup"
        assert t.admit(1, tags.GRAD, 0, 2) == "fresh"
        assert t.admit(1, tags.GRAD, 0, 2) == "dup"
        # new incarnation resets the horizon
        assert t.admit(1, tags.GRAD, 1, 1) == "fresh"
        # the dead incarnation's stragglers are stale
        assert t.admit(1, tags.GRAD, 0, 3) == "stale"

    def test_channels_are_independent(self):
        t = DedupTable()
        assert t.admit(1, tags.GRAD, 0, 1) == "fresh"
        assert t.admit(1, tags.PARAM_PUSH, 0, 1) == "fresh"
        assert t.admit(2, tags.GRAD, 0, 1) == "fresh"

    def test_state_roundtrip(self):
        t = DedupTable()
        t.admit(1, tags.GRAD, 2, 7)
        t.admit(3, tags.PARAM_PUSH, 0, 4)
        t2 = DedupTable()
        t2.restore(t.state())
        assert t2.admit(1, tags.GRAD, 2, 7) == "dup"
        assert t2.admit(3, tags.PARAM_PUSH, 0, 5) == "fresh"


class TestLeaseRegistry:
    def test_expiry_only_after_first_beat(self):
        now = [0.0]
        reg = LeaseRegistry([1, 2], ttl_s=1.0, clock=lambda: now[0])
        reg.arm(1, 0, heartbeats=True)
        reg.arm(2, 0, heartbeats=False)  # never promised beats
        # nobody beat yet: nobody is on the clock (the seeding-phase
        # grace — arming at INIT would evict a slow seeder mid-push)
        now[0] = 5.0
        assert reg.expired() == []
        reg.renew(1, 0)  # first beat arms the clock
        reg.renew(2, 0)  # never promised: renew is a no-op
        now[0] = 5.5
        assert reg.expired() == []
        now[0] = 6.5
        assert reg.expired() == [1]
        reg.renew(1, 0)
        assert reg.expired() == []

    def test_stale_epoch_beat_does_not_renew(self):
        now = [0.0]
        reg = LeaseRegistry([1], ttl_s=1.0, clock=lambda: now[0])
        reg.arm(1, 5, heartbeats=True)
        reg.renew(1, 5)  # first beat: on the clock from t=0
        now[0] = 0.9
        reg.renew(1, 4)  # dead incarnation's leftover beacon
        now[0] = 1.5
        assert reg.expired() == [1]

    def test_eviction_and_rejoin_lifecycle(self):
        reg = LeaseRegistry([1, 2], ttl_s=0.0)
        reg.evict(1)
        assert reg.state(1) == EVICTED and reg.gone(1)
        assert not reg.all_done()
        reg.stop(2)
        assert reg.all_done()
        reg.rejoin(1, epoch=1)
        assert not reg.gone(1) and reg.epoch(1) == 1


class TestRetryPolicy:
    def test_backoff_caps_and_jitter_is_deterministic(self):
        cfg = FTConfig(op_deadline_s=1.0, max_retries=10,
                       backoff_base_s=0.01, backoff_cap_s=0.05)
        pol = RetryPolicy(cfg, key=3)
        seq = [pol.backoff_s(a) for a in range(1, 11)]
        assert seq == [RetryPolicy(cfg, key=3).backoff_s(a)
                       for a in range(1, 11)]
        assert max(seq) <= 0.05 * 1.5 + 1e-9
        assert seq[0] >= 0.01
        # a different key decorrelates
        assert seq != [RetryPolicy(cfg, key=4).backoff_s(a)
                       for a in range(1, 11)]


# ---------------------------------------------------------------------------
# end-to-end: retry + dedup against an injected-fault PS topology


def launch_ft(nservers, nclients, client_plans=None, server_plan=None,
              client_ft=FAST_FT, server_ft=None, rule="add", codec=None):
    """FT PS topology over LocalRouter with FaultyTransport seams.
    Returns (servers, clients, threads, client_transports)."""
    n = nservers + nclients
    router = LocalRouter(n)
    sranks = list(range(nservers))
    cranks = list(range(nservers, n))
    server_ft = server_ft or FTConfig(rejoin=True)
    servers, threads = [], []
    for r in sranks:
        ep = router.endpoint(r)
        if server_plan is not None:
            ep = FaultyTransport(ep, server_plan)
        servers.append(ParamServer(r, cranks, ep, rule=rule, ft=server_ft))
        threads.append(threading.Thread(target=servers[-1].start, daemon=True))
    for t in threads:
        t.start()
    transports, clients = [], []
    for i, r in enumerate(cranks):
        ep = router.endpoint(r)
        plan = (client_plans or {}).get(i)
        if plan is not None:
            ep = FaultyTransport(ep, plan)
        transports.append(ep)
        clients.append(ParamClient(r, sranks, ep,
                                   seed_servers=(r == cranks[0]),
                                   codec=codec, ft=client_ft))
    return servers, clients, threads, transports


def run_lockstep(clients, grads_per_round, rounds):
    """Lockstep rounds: each client ships its grad and awaits the acks
    before the next client moves — pins the cross-client apply order so
    faulty and fault-free runs are bitwise-comparable."""
    for r in range(rounds):
        for i, c in enumerate(clients):
            c.grad[:] = grads_per_round(i, r)
            c.async_send_grad()
            c.wait()


class TestRetryDedupEndToEnd:
    def _final_params(self, client_plans, server_plan, rounds=4,
                      nservers=2, nclients=2, codec=None, size=64):
        rng = np.random.default_rng(42)
        w0 = rng.normal(size=size).astype(np.float32)
        gtab = rng.normal(size=(nclients, rounds, size)).astype(np.float32)
        servers, clients, threads, transports = launch_ft(
            nservers, nclients, client_plans=client_plans,
            server_plan=server_plan, codec=codec)
        params = []
        starters = []
        for c in clients:
            p = w0.copy() if not params else np.zeros_like(w0)
            params.append(p)
            starters.append(threading.Thread(
                target=c.start, args=(p, np.zeros_like(w0)), daemon=True))
        for t in starters:
            t.start()
        join_all(starters)
        run_lockstep(clients, lambda i, r: gtab[i, r], rounds)
        clients[0].async_recv_param()
        clients[0].wait()
        for c in clients:
            c.stop()
        join_all(threads)
        stats = {
            "applied": sum(s.grads_applied for s in servers),
            "dups": sum(s.dup_ops for s in servers),
            "retries": sum(c.retries for c in clients),
        }
        return params[0].copy(), stats

    def test_drop_and_dup_run_matches_fault_free_bitwise(self):
        """The acceptance matrix: every 3rd client data message dropped,
        every 4th duplicated; every 3rd server reply dropped.  The final
        params must equal the fault-free run's final params *bitwise* —
        retry + dedup + seq-matched acks leave no trace in the math."""
        clean, clean_stats = self._final_params(None, None)
        client_plans = {
            i: FaultPlan(seed=i, drop_every=3, dup_every=4, tags=DATA_TAGS)
            for i in range(2)
        }
        server_plan = FaultPlan(seed=9, drop_every=3, tags=REPLY_TAGS)
        faulty, stats = self._final_params(client_plans, server_plan)
        np.testing.assert_array_equal(clean, faulty)
        assert stats["retries"] > 0, "the plan never actually bit"
        assert stats["dups"] > 0, "no duplicate was ever admitted"
        assert stats["applied"] == clean_stats["applied"]

    def test_int8_error_feedback_survives_retries(self):
        """Dropped replies force resends of quantized grads; the staged
        encode-once frames + server dedup must keep the error-feedback
        telescope exact: bitwise-equal params vs the fault-free int8 run."""
        clean, _ = self._final_params(None, None, codec="int8", size=2048)
        server_plan = FaultPlan(seed=5, drop_every=2, tags=REPLY_TAGS)
        faulty, stats = self._final_params(None, server_plan,
                                           codec="int8", size=2048)
        np.testing.assert_array_equal(clean, faulty)
        assert stats["retries"] > 0 and stats["dups"] > 0

    def test_exhausted_retries_fail_loudly_never_hang(self):
        """A severed server link must surface as RetryExhausted from the
        client's wait — the never-hang contract."""
        servers, clients, threads, transports = launch_ft(
            1, 1,
            client_plans={0: FaultPlan(tags=DATA_TAGS)},
            client_ft=FTConfig(op_deadline_s=0.05, max_retries=2,
                               backoff_base_s=0.005, backoff_cap_s=0.01),
        )
        (client,), (ct,) = clients, transports
        w0 = np.ones(8, np.float32)
        param, grad = w0.copy(), np.zeros_like(w0)
        client.start(param, grad)
        ct.sever(0)
        grad[:] = 1.0
        client.async_send_grad()
        t0 = time.monotonic()
        with pytest.raises(TaskError) as err:
            client.wait()
        assert isinstance(err.value.cause, RetryExhausted)
        assert time.monotonic() - t0 < 10.0
        for s in servers:
            s.live.stop()
        join_all(threads)

    def test_param_read_retries_and_discards_stale_snapshots(self):
        """Dropped PARAM replies: the read retries (same seq) and a later
        duplicate snapshot must not satisfy a newer request."""
        server_plan = FaultPlan(seed=2, drop_every=2,
                                tags=frozenset({tags.PARAM}))
        servers, clients, threads, _ = launch_ft(1, 1,
                                                 server_plan=server_plan)
        (client,) = clients
        w0 = np.arange(16, dtype=np.float32)
        param, grad = w0.copy(), np.zeros_like(w0)
        client.start(param, grad)
        for i in range(4):
            grad[:] = 1.0
            client.async_send_grad()
            client.async_recv_param()
            client.wait()
            np.testing.assert_array_equal(param, w0 + (i + 1))
        assert client.retries > 0
        client.stop()
        join_all(threads)


# ---------------------------------------------------------------------------
# heartbeats, leases, eviction, rejoin


HB_FT = FTConfig(heartbeat_s=0.02, op_deadline_s=0.5, max_retries=4,
                 backoff_base_s=0.005, backoff_cap_s=0.02)


class TestHeartbeatLeaseEviction:
    def test_heartbeats_flow_and_renew(self):
        servers, clients, threads, _ = launch_ft(
            1, 1, client_ft=HB_FT,
            server_ft=FTConfig(lease_ttl_s=0.5, rejoin=True))
        (client,) = clients
        w0 = np.ones(8, np.float32)
        client.start(w0.copy(), np.zeros_like(w0))
        deadline = time.monotonic() + 5
        while servers[0].heartbeats_seen < 3 and time.monotonic() < deadline:
            client.ping()
            time.sleep(0.005)
        assert servers[0].heartbeats_seen >= 3
        assert client.heartbeats_sent >= 3
        client.stop()
        join_all(threads)

    def test_lease_expiry_evicts_without_stalling_survivors(self):
        """The acceptance scenario: one client goes silent; its lease
        expires; the server evicts it, keeps serving the survivor, and
        the stop protocol completes without the dead client's STOP."""
        servers, clients, threads, transports = launch_ft(
            1, 2,
            client_plans={1: FaultPlan()},  # wrap c2 so we can sever it
            client_ft=HB_FT,
            server_ft=FTConfig(lease_ttl_s=0.15, rejoin=True))
        c1, c2 = clients
        w0 = np.ones(8, np.float32)
        bufs = [(w0.copy(), np.zeros_like(w0)),
                (np.zeros_like(w0), np.zeros_like(w0))]
        starters = [threading.Thread(target=c.start, args=bufs[i], daemon=True)
                    for i, c in enumerate(clients)]
        for t in starters:
            t.start()
        join_all(starters)
        # the lease arms on c2's first delivered beat (not at INIT —
        # arming before the seeding phase would evict mid-seed)
        deadline = time.monotonic() + 10
        while servers[0].heartbeats_seen < 2 and time.monotonic() < deadline:
            c2.ping()
            c2.wait()
            time.sleep(0.005)
        transports[1].sever(0)  # c2 "crashes": nothing reaches the server
        deadline = time.monotonic() + 10
        while (servers[0].leases.state(clients[1].rank) != EVICTED
               and time.monotonic() < deadline):
            c1.ping()
            time.sleep(0.005)
        assert servers[0].leases.state(c2.rank) == EVICTED
        assert c2.rank not in servers[0].grad_bufs  # staging released
        # survivor is unaffected
        p1, g1 = bufs[0]
        g1[:] = 2.0
        c1.async_send_grad()
        c1.async_recv_param()
        c1.wait()
        np.testing.assert_array_equal(p1, w0 + 2.0)
        c1.stop()
        join_all(threads)  # completes with only the survivor's STOP
        assert servers[0].leases.evictions == 1

    def test_evicted_client_rejoins_with_bumped_epoch(self):
        servers, clients, threads, transports = launch_ft(
            1, 2, client_plans={1: FaultPlan()}, client_ft=HB_FT,
            server_ft=FTConfig(lease_ttl_s=0.15, rejoin=True))
        c1, c2 = clients
        w0 = np.ones(8, np.float32)
        bufs = [(w0.copy(), np.zeros_like(w0)),
                (np.zeros_like(w0), np.zeros_like(w0))]
        starters = [threading.Thread(target=c.start, args=bufs[i], daemon=True)
                    for i, c in enumerate(clients)]
        for t in starters:
            t.start()
        join_all(starters)
        bufs[1][1][:] = 1.0
        c2.async_send_grad()
        c2.wait()
        deadline = time.monotonic() + 10
        while servers[0].heartbeats_seen < 2 and time.monotonic() < deadline:
            c2.ping()
            c2.wait()
            time.sleep(0.005)
        transports[1].sever(0)  # crash
        deadline = time.monotonic() + 10
        while (servers[0].leases.state(c2.rank) != EVICTED
               and time.monotonic() < deadline):
            c1.ping()
            time.sleep(0.005)
        assert servers[0].leases.state(c2.rank) == EVICTED
        # the restarted incarnation: same rank, epoch + 1, no seeding
        c2b = ParamClient(
            c2.rank, [0], transports[1].inner,
            ft=FTConfig(heartbeat_s=0.02, op_deadline_s=0.5, max_retries=4,
                        backoff_base_s=0.005, epoch=1))
        p2b, g2b = np.zeros_like(w0), np.zeros_like(w0)
        starter = threading.Thread(target=c2b.start, args=(p2b, g2b),
                                   daemon=True)
        starter.start()
        join_all([starter], timeout=10)
        assert servers[0].rejoins == 1
        c2b.async_recv_param()
        c2b.wait()
        np.testing.assert_array_equal(p2b, w0 + 1.0)  # pre-crash state kept
        g2b[:] = 3.0
        c2b.async_send_grad()
        c2b.wait()
        p1, g1 = bufs[0]
        c1.async_recv_param()
        c1.wait()
        np.testing.assert_array_equal(p1, w0 + 4.0)
        c1.stop()
        c2b.stop()
        join_all(threads)


# ---------------------------------------------------------------------------
# server checkpoint / restart


class TestServerRestart:
    def test_restart_resumes_retried_ops_without_double_apply(self, tmp_path):
        """Kill the server after a checkpoint; the client's in-flight
        retry lands on the restarted process.  The checkpointed dedup
        table must admit the already-applied op as DUP, and the op issued
        into the void must apply exactly once."""
        router = LocalRouter(2)
        s1 = ParamServer(0, [1], router.endpoint(0), rule="adam")
        t = threading.Thread(target=s1.start, daemon=True)
        t.start()
        client = ParamClient(
            1, [0], router.endpoint(1), seed_servers=True,
            ft=FTConfig(op_deadline_s=0.2, max_retries=30,
                        backoff_base_s=0.01, backoff_cap_s=0.05))
        w0 = np.ones(12, np.float32)
        param, grad = w0.copy(), np.zeros_like(w0)
        client.start(param, grad)
        grad[:] = 1.0
        client.async_send_grad()
        client.wait()
        s1.live.stop()
        t.join(5)
        path = s1.save_state(tmp_path)
        assert "server0_" in str(path)  # stamped version
        # ops into the void: retried until the replacement serves them
        client.async_send_grad()
        client.async_recv_param()
        s2 = ParamServer(0, [1], router.endpoint(0), rule="adam",
                         ft=FTConfig(rejoin=True))
        s2.restore_state(tmp_path / "server0_latest.npz")
        t2 = threading.Thread(target=s2.start, daemon=True)
        t2.start()
        client.wait()
        client.stop()
        join_all([t2])
        assert s2.grads_applied == 2  # restored count + exactly one more

    def test_stamped_history_is_pruned(self, tmp_path):
        from mpit_tpu.utils.checkpoint import save_server_state

        for _ in range(6):
            save_server_state(tmp_path, 0, 0, 4, np.zeros(4, np.float32),
                              {}, keep=3)
            time.sleep(0.002)  # distinct millisecond stamps
        stamped = [p for p in tmp_path.glob("server0_*.npz")
                   if p.name[len("server0_"):-len(".npz")].isdigit()]
        assert len(stamped) == 3
        assert (tmp_path / "server0_latest.npz").exists()

    def test_checkpoint_meta_carries_ft_state(self, tmp_path):
        servers, clients, threads, _ = launch_ft(1, 1, client_ft=FAST_FT)
        (client,) = clients
        w0 = np.ones(8, np.float32)
        param, grad = w0.copy(), np.zeros_like(w0)
        client.start(param, grad)
        grad[:] = 1.0
        client.async_send_grad()
        client.wait()
        client.stop()
        join_all(threads)
        path = servers[0].save_state(tmp_path)
        from mpit_tpu.utils.checkpoint import load_server_state

        *_rest, meta = load_server_state(path)
        assert meta["clients"]["1"]["framed"] is True
        assert meta["dedup"]  # the admitted seqs are recorded
        s2 = ParamServer(0, [1], LocalRouter(2).endpoint(0))
        s2.restore_state(path)
        assert s2.dedup.admit(1, tags.GRAD, 0, 1) == "dup"


# ---------------------------------------------------------------------------
# the property test: any {drop, delay, dup} plan completes bitwise or
# fails loudly — never hangs


# ---------------------------------------------------------------------------
# chaos soak: SIGKILL a live worker process mid-DOWNPOUR, supervisor
# restarts it, it rejoins via INIT v3, the run converges


@pytest.mark.slow
def test_chaos_soak_sigkill_worker_rejoins_and_converges(tmp_path, monkeypatch):
    """np=4 DOWNPOUR gang over TCP with the FT posture on.  The
    supervisor SIGKILLs worker rank 3 mid-run and respawns it as epoch 1
    (MPIT_FT_REJOIN): it re-announces via INIT v3, pulls the live center,
    and finishes training.  Both workers must land in the fault-free
    loss envelope (the bar the non-chaos np4 topology tests assert)."""
    import socket

    from mpit_tpu.ft.supervisor import RestartPolicy, supervise_gang
    from mpit_tpu.train.launch import LAUNCH_DEFAULTS, device_env_overrides

    socks = [socket.socket() for _ in range(4)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    addrs = ",".join(f"127.0.0.1:{s.getsockname()[1]}" for s in socks)
    for s in socks:
        s.close()
    # TCP reconnect window: the restarted rank re-binds its address and
    # redials; peers re-handshake instead of failing loudly.
    monkeypatch.setenv("MPIT_TCP_RECONNECT_S", "60")
    cfg = LAUNCH_DEFAULTS.merged(
        # epochs sized so the +12s kill lands mid-training and the
        # surviving worker is still running through the whole restart
        # cycle (~0.15s/epoch on the 1-core CI box).
        np=4, opt="downpour", lr=0.2, su=1, epochs=300, batch=64, side=8,
        master_freq=2, device_policy="cpu", transport="tcp",
        tcp_addrs=addrs,
        # Lease TTL comfortably above the restart cycle: the replacement
        # normally rejoins while still ACTIVE (generation supersede); if
        # a slow box pushes past the TTL, eviction-then-rejoin also works.
        ft_heartbeat_s=0.25, ft_lease_ttl_s=20.0, ft_op_deadline_s=5.0,
        supervise=2,
        server_ckpt_dir=str(tmp_path), server_ckpt_interval=2.0,
    )
    results = supervise_gang(
        "mpit_tpu.train.launch", cfg, timeout=600,
        policy=RestartPolicy(max_restarts=2, restart_delay_s=0.5),
        env_overrides=device_env_overrides(cfg, 4),
        server_ranks=[0, 2],
        chaos_kill_rank=3, chaos_kill_after_s=12.0,
    )
    roles = {r: v["role"] for r, v in results.items()}
    assert roles == {0: "server", 1: "worker", 2: "server", 3: "worker"}
    workers = [v for v in results.values() if v["role"] == "worker"]
    # the fault-free envelope from the np4 topology tests
    assert all(w["final_test_err"] < 0.8 for w in workers)
    assert all(v["grads_applied"] > 0 for v in results.values()
               if v["role"] == "server")


@pytest.mark.parametrize("seed", range(5))
def test_property_fault_plans_never_hang(seed):
    """Seed-deterministic random plans over {drop, delay, dup} on <= 3
    clients: the run either completes with bitwise-correct final params
    or raises (RetryExhausted / TaskError) — and always finishes inside
    the hard timeout.  INIT stays clean (membership is the supervisor's
    problem, not retry's); STOP loss is covered by lease eviction."""
    rng = np.random.default_rng(seed)
    nclients = int(rng.integers(1, 4))
    rounds = 3
    size = 32
    w0 = rng.normal(size=size).astype(np.float32)
    gtab = rng.normal(size=(nclients, rounds, size)).astype(np.float32)

    def run(client_plans, server_plan, box):
        servers, clients = [], []
        try:
            servers, clients, threads, _ = launch_ft(
                2, nclients, client_plans=client_plans,
                server_plan=server_plan,
                client_ft=FTConfig(heartbeat_s=0.02, op_deadline_s=0.15,
                                   max_retries=6, backoff_base_s=0.005,
                                   backoff_cap_s=0.02),
                server_ft=FTConfig(lease_ttl_s=1.0, rejoin=True))
            params = []
            starters = []
            for i, c in enumerate(clients):
                p = w0.copy() if i == 0 else np.zeros(size, np.float32)
                g = np.zeros(size, np.float32)
                params.append((p, g))
                starters.append(threading.Thread(
                    target=c.start, args=(p, g), daemon=True))
            for t in starters:
                t.start()
            join_all(starters, timeout=20)
            for r in range(rounds):
                for i, c in enumerate(clients):
                    params[i][1][:] = gtab[i, r]
                    c.async_send_grad()
                    c.wait()
            clients[0].async_recv_param()
            clients[0].wait()
            for c in clients:
                c.stop()
            join_all(threads, timeout=20)
            box["params"] = params[0][0].copy()
        except (TaskError, RetryExhausted, AssertionError) as exc:
            box["error"] = exc  # loud is an acceptable outcome
            for c in clients:
                c.live.stop()
            for s in servers:
                s.live.stop()

    clean: dict = {}
    run(None, None, clean)
    assert "params" in clean, f"fault-free run failed: {clean.get('error')}"

    client_plans = {
        i: FaultPlan(seed=seed * 17 + i, drop_rate=0.08, dup_rate=0.08,
                     delay_rate=0.15, delay_polls=4, tags=DATA_TAGS)
        for i in range(nclients)
    }
    server_plan = FaultPlan(seed=seed * 31 + 7, drop_rate=0.08,
                            dup_rate=0.08, delay_rate=0.15, delay_polls=4,
                            tags=REPLY_TAGS)
    box: dict = {}
    worker = threading.Thread(target=run,
                              args=(client_plans, server_plan, box),
                              daemon=True)
    worker.start()
    worker.join(90)  # the hard timeout: a hang is the one forbidden outcome
    assert not worker.is_alive(), "faulty run HUNG (never-hang contract broken)"
    if "params" in box:
        np.testing.assert_array_equal(clean["params"], box["params"])
    else:
        assert "error" in box  # failed loudly
