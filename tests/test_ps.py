"""Integration tests: ParamServer + ParamClient over the in-process
transport — the analog of the reference's mpirun-on-one-host test mode
(SURVEY.md section 4), with real assertions.

Topology helpers run each server's blocking event loop on its own thread
(the per-rank process analog) while clients drive from the test thread.
"""

import contextlib
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.comm.local import LocalRouter
from mpit_tpu.optim import rules
from mpit_tpu.optim.downpour import Downpour
from mpit_tpu.optim.shells import SingleWorker
from mpit_tpu.ps import ParamClient, ParamServer, Shard, shard_layout


class TestShardLayout:
    def test_even_split(self):
        assert shard_layout(12, 3) == [Shard(0, 4), Shard(4, 4), Shard(8, 4)]

    def test_remainder_goes_to_last(self):
        # floor(10/3)=3: [0,3) [3,6) [6,10) (reference pclient.lua:111-129)
        assert shard_layout(10, 3) == [Shard(0, 3), Shard(3, 3), Shard(6, 4)]

    def test_single_server_takes_all(self):
        assert shard_layout(7, 1) == [Shard(0, 7)]

    def test_errors(self):
        with pytest.raises(ValueError):
            shard_layout(2, 3)
        with pytest.raises(ValueError):
            shard_layout(10, 0)


@contextlib.contextmanager
def launch(nservers, nclients, rule="add", single_mode=False, codec=None,
           server_codec=None):
    """PS topology: servers on ranks [0, nservers) in threads, clients on
    the following ranks, driven by the caller.  Teardown force-stops any
    still-running server so a failed assertion can't leave busy-spinning
    threads behind to starve later tests.  ``codec`` sets the clients'
    announced codec; ``server_codec`` pins the servers (mismatch tests)."""
    n = nservers + nclients
    router = LocalRouter(n)
    sranks = list(range(nservers))
    cranks = list(range(nservers, n))
    servers = [
        ParamServer(r, cranks, router.endpoint(r), rule=rule,
                    single_mode=single_mode, codec=server_codec)
        for r in sranks
    ]
    threads = [threading.Thread(target=s.start, daemon=True) for s in servers]
    for t in threads:
        t.start()
    clients = [
        ParamClient(r, sranks, router.endpoint(r),
                    seed_servers=(r == cranks[0]), codec=codec)
        for r in cranks
    ]
    try:
        yield servers, clients, threads
    finally:
        for s in servers:
            s.live.stop()
        for t in threads:
            t.join(5)


def join_all(threads, timeout=30):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "server did not stop (stop-protocol hang)"


class TestPSBasic:
    def test_seed_push_pull_single_shard(self, rng):
        w0 = rng.normal(size=16).astype(np.float32)
        with launch(1, 1) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)

            # Push a delta; server plain-adds; pull back.  Per-server op
            # chaining guarantees the pull sees this client's own push.
            grad[:] = 1.0
            client.async_send_grad()
            client.async_recv_param()
            client.wait()
            np.testing.assert_allclose(param, w0 + 1.0, rtol=1e-6)

            client.stop()
            join_all(threads)
            assert servers[0].grads_applied == 1
            assert servers[0].params_served == 1

    def test_two_servers_shard_correctly(self, rng):
        w0 = rng.normal(size=10).astype(np.float32)  # shards: [0,5) [5,10)
        with launch(2, 1) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)

            delta = rng.normal(size=10).astype(np.float32)
            grad[:] = delta
            client.async_send_grad()
            client.async_recv_param()
            client.wait()
            np.testing.assert_allclose(param, w0 + delta, rtol=1e-5)
            # Each server holds exactly its contiguous slice.
            np.testing.assert_allclose(
                np.asarray(servers[0].param), (w0 + delta)[:5], rtol=1e-5)
            np.testing.assert_allclose(
                np.asarray(servers[1].param), (w0 + delta)[5:], rtol=1e-5)

            client.stop()
            join_all(threads)

    def test_two_clients_share_center(self, rng):
        w0 = rng.normal(size=8).astype(np.float32)
        with launch(1, 2) as (servers, (c1, c2), threads):
            p1, g1 = w0.copy(), np.zeros_like(w0)
            p2, g2 = np.zeros_like(w0), np.zeros_like(w0)
            # Clients must start concurrently (each is its own process in
            # the reference): the server's init phase waits on both, and
            # the seeder's start() blocks on the seed ack.
            t1 = threading.Thread(target=c1.start, args=(p1, g1), daemon=True)
            t2 = threading.Thread(target=c2.start, args=(p2, g2), daemon=True)
            t1.start()
            t2.start()
            t1.join(30)
            t2.join(30)
            assert not t1.is_alive() and not t2.is_alive(), "client start hung"

            # c2 pulls: sees the seed from c1.
            c2.async_recv_param()
            c2.wait()
            np.testing.assert_allclose(p2, w0, rtol=1e-6)

            # Both push deltas (awaiting acks); then c1 pulls the sum.
            g1[:] = 1.0
            c1.async_send_grad()
            c1.wait()
            g2[:] = 2.0
            c2.async_send_grad()
            c2.wait()
            c1.async_recv_param()
            c1.wait()
            np.testing.assert_allclose(p1, w0 + 3.0, rtol=1e-6)

            c1.stop()
            c2.stop()
            join_all(threads)

    def test_server_side_adam(self, rng):
        """Clients ship raw grads; servers apply Adam — result must match a
        local Adam rollout on the full vector."""
        w0 = rng.normal(size=12).astype(np.float32)
        grads = [rng.normal(size=12).astype(np.float32) for _ in range(3)]
        hp = dict(lr=1e-2, beta1=0.9, beta2=0.999, epsilon=1e-8)
        with launch(2, 1, rule=rules.make("adam", **hp)) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            for g in grads:
                grad[:] = g
                client.async_send_grad()
                client.wait()
            client.async_recv_param()
            client.wait()
            client.stop()
            join_all(threads)

        rule = rules.make("adam", **hp)
        p = jnp.asarray(w0)
        st = rule.init(p)
        for g in grads:
            p, st = rule.apply(p, jnp.asarray(g), st)
        np.testing.assert_allclose(param, np.asarray(p), rtol=1e-5)

    def test_reset_retargets_buffers(self, rng):
        w0 = rng.normal(size=6).astype(np.float32)
        with launch(1, 1) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)

            alt_param = np.zeros_like(w0)
            alt_grad = np.full_like(w0, 0.5)
            client.reset(alt_param, alt_grad)
            client.async_send_grad()
            client.async_recv_param()
            client.wait()
            np.testing.assert_allclose(alt_param, w0 + 0.5, rtol=1e-6)
            np.testing.assert_allclose(param, w0, rtol=1e-6)  # original untouched

            client.stop()
            join_all(threads)

    def test_reset_length_mismatch(self, rng):
        w0 = rng.normal(size=6).astype(np.float32)
        with launch(1, 1) as (servers, (client,), threads):
            client.start(w0.copy(), np.zeros_like(w0))
            with pytest.raises(ValueError):
                client.reset(np.zeros(7, np.float32), np.zeros(7, np.float32))
            client.stop()
            join_all(threads)


class TestPSWithOptimizers:
    def test_downpour_su1_end_to_end(self, rng):
        """Full stack: Downpour -> ParamClient -> LocalTransport ->
        ParamServer(plain add) matches serial SGD."""
        w0 = rng.normal(size=8).astype(np.float32)
        lr, steps = 0.1, 5
        with launch(2, 1) as (servers, (client,), threads):
            def vgf(w, target):
                return 0.5 * jnp.sum((w - target) ** 2), w - target

            opt = Downpour(vgf, client, lr=lr, su=1)
            w = opt.start(jnp.asarray(w0))
            target = jnp.zeros(8)
            for _ in range(steps):
                w, _ = opt.step(w, target)
            opt.stop()
            join_all(threads)

        ref = w0.astype(np.float64)
        for _ in range(steps):
            ref = ref - lr * ref
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-4)

    def test_single_worker_mirror(self, rng):
        """SingleWorker pushes whole params; single_mode server mirrors them."""
        w0 = rng.normal(size=6).astype(np.float32)
        with launch(1, 1, single_mode=True) as (servers, (client,), threads):
            def vgf(w, target):
                return 0.5 * jnp.sum((w - target) ** 2), w - target

            opt = SingleWorker(vgf, client, rule="adagrad", lr=0.1)
            w = opt.start(jnp.asarray(w0))
            for _ in range(3):
                w, _ = opt.step(w, jnp.zeros(6))
            opt.stop()
            join_all(threads)
            np.testing.assert_allclose(
                np.asarray(servers[0].param), np.asarray(w), rtol=1e-5)


class TestWireCodecs:
    """INIT v2 negotiation, quantized transfers, the snapshot cache, and
    the fail-loudly paths (legacy interop / mismatch / unknown id)."""

    @pytest.mark.parametrize("codec,tol", [("bf16", 2.0**-7), ("int8", 1 / 64)])
    def test_seed_push_pull_quantized(self, rng, codec, tol):
        w0 = rng.normal(size=3000).astype(np.float32)
        with launch(2, 1, codec=codec) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            grad[:] = 1.0
            client.async_send_grad()
            client.async_recv_param()
            client.wait()
            scale = np.abs(w0).max() + 1.0
            # seed + grad + snapshot each quantize once
            np.testing.assert_allclose(param, w0 + 1.0, atol=4 * tol * scale)
            client.stop()
            join_all(threads)
            assert all(s._codecs[2].name == codec for s in servers)

    def test_env_codec_drives_negotiation(self, rng, monkeypatch):
        monkeypatch.setenv("MPIT_PS_CODEC", "bf16")
        w0 = rng.normal(size=64).astype(np.float32)
        with launch(1, 1) as (servers, (client,), threads):
            assert client.codec.name == "bf16"
            client.start(w0.copy(), np.zeros_like(w0))
            client.stop()
            join_all(threads)
            assert servers[0]._codecs[1].name == "bf16"

    def test_legacy_16_byte_init_interops_as_none(self, rng):
        """A v1 peer announcing [offset, size] must be served with the
        identity codec — the mixed-version deployment case."""
        w0 = rng.normal(size=16).astype(np.float32)
        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0))
        t = threading.Thread(target=server.start, daemon=True)
        t.start()
        try:
            wire = router.endpoint(1)
            from mpit_tpu.ps import tags

            # Hand-rolled v1 client: legacy INIT, seed, grad, pull.
            wire.send(np.asarray([0, 16], dtype=np.int64), 0, tags.INIT)
            wire.send(w0, 0, tags.PARAM_PUSH)
            wire.recv(0, tags.PARAM_PUSH_ACK)
            wire.send(np.full(16, 2.0, np.float32), 0, tags.GRAD)
            wire.recv(0, tags.GRAD_ACK)
            wire.send(tags.EMPTY, 0, tags.PARAM_REQ)
            out = np.zeros(16, np.float32)
            while not wire.iprobe(0, tags.PARAM):
                pass
            wire.recv(0, tags.PARAM, out=out)
            np.testing.assert_allclose(out, w0 + 2.0, rtol=1e-6)
            assert server._codecs[1].name == "none"
            wire.send(tags.EMPTY, 0, tags.STOP)
            join_all([t])
        finally:
            server.live.stop()

    def test_codec_mismatch_fails_loudly(self, rng):
        """A server pinned to one codec must reject a client announcing
        another at INIT — not decode frames into corrupt params."""
        from mpit_tpu.aio.scheduler import TaskError

        n = 2
        router = LocalRouter(n)
        server = ParamServer(0, [1], router.endpoint(0), codec="bf16")
        failure = []

        def run_server():
            try:
                server.start()
            except TaskError as exc:
                failure.append(exc)

        t = threading.Thread(target=run_server, daemon=True)
        t.start()
        client = ParamClient(1, [0], router.endpoint(1), codec="int8")
        w0 = rng.normal(size=8).astype(np.float32)
        client.start(w0.copy(), np.zeros_like(w0))  # INIT only (no seeding)
        t.join(10)
        assert not t.is_alive(), "mismatched server neither failed nor stopped"
        assert failure, "server accepted a mismatched codec announcement"
        assert "codec negotiation mismatch" in str(failure[0].cause)

    def test_unknown_wire_id_fails_loudly(self):
        from mpit_tpu.aio.scheduler import TaskError
        from mpit_tpu.ps import tags

        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0))
        failure = []

        def run_server():
            try:
                server.start()
            except TaskError as exc:
                failure.append(exc)

        t = threading.Thread(target=run_server, daemon=True)
        t.start()
        router.endpoint(1).send(
            np.asarray([0, 8, 99], dtype=np.int64), 0, tags.INIT)
        t.join(10)
        assert not t.is_alive()
        assert failure and "unknown codec wire id" in str(failure[0].cause)

    def test_bad_init_length_fails_loudly(self):
        from mpit_tpu.aio.scheduler import TaskError
        from mpit_tpu.ps import tags

        router = LocalRouter(2)
        server = ParamServer(0, [1], router.endpoint(0))
        failure = []

        def run_server():
            try:
                server.start()
            except TaskError as exc:
                failure.append(exc)

        t = threading.Thread(target=run_server, daemon=True)
        t.start()
        router.endpoint(1).send(
            np.asarray([0, 8, 0, 0], dtype=np.int64), 0, tags.INIT)
        t.join(10)
        assert not t.is_alive()
        assert failure and "INIT announcement" in str(failure[0].cause)

    def test_snapshot_cache_one_copy_per_version(self, rng):
        """N pulls of one committed version = one device->host copy +
        one encode; a grad apply bumps the version and invalidates."""
        w0 = rng.normal(size=256).astype(np.float32)
        with launch(1, 1, codec="int8") as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            for _ in range(3):  # same version three times
                client.async_recv_param()
                client.wait()
            s = servers[0]
            assert s.snapshot_copies == 1
            assert s.snapshot_hits == 2
            grad[:] = 1.0
            client.async_send_grad()
            client.wait()
            client.async_recv_param()
            client.wait()
            assert s.snapshot_copies == 2  # new version, one new copy
            client.stop()
            join_all(threads)

    def test_mixed_codec_clients_negotiate_per_pair(self, rng):
        """codec=None servers adopt each client's announcement — a bf16
        client and a none client share one server."""
        w0 = rng.normal(size=128).astype(np.float32)
        n = 3
        router = LocalRouter(n)
        server = ParamServer(0, [1, 2], router.endpoint(0))
        t = threading.Thread(target=server.start, daemon=True)
        t.start()
        c1 = ParamClient(1, [0], router.endpoint(1), seed_servers=True,
                         codec="none")
        c2 = ParamClient(2, [0], router.endpoint(2), codec="bf16")
        p1, g1 = w0.copy(), np.zeros_like(w0)
        p2, g2 = np.zeros_like(w0), np.zeros_like(w0)
        t1 = threading.Thread(target=c1.start, args=(p1, g1), daemon=True)
        t2 = threading.Thread(target=c2.start, args=(p2, g2), daemon=True)
        t1.start(); t2.start()
        t1.join(30); t2.join(30)
        assert not t1.is_alive() and not t2.is_alive(), "client start hung"
        c2.async_recv_param()
        c2.wait()
        np.testing.assert_allclose(p2, w0, rtol=2.0**-7, atol=1e-6)
        assert server._codecs[1].name == "none"
        assert server._codecs[2].name == "bf16"
        c1.stop(); c2.stop()
        join_all([t])

    def test_int8_error_feedback_sums_over_rounds(self, rng):
        """Repeated identical grads must accumulate to ~T*g on the server
        (EF re-ships each round's quantization error), far tighter than
        T independent quantization errors."""
        w0 = np.zeros(2048, np.float32)
        g = rng.normal(size=2048).astype(np.float32)
        T = 16
        with launch(1, 1, codec="int8") as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            grad[:] = g
            for _ in range(T):
                client.async_send_grad()
                client.wait()
            client.async_recv_param()
            client.wait()
            client.stop()
            join_all(threads)
        # EF bound: |sum - T*g| <= residual + one snapshot quantization,
        # each bounded by one block scale — NOT T * scale.
        scale = np.abs(g).max() * T / 127.0
        assert np.abs(param - T * g).max() <= 2.5 * scale
        assert client.residual_norm() > 0.0  # residual is live

    def test_residual_free_codecs_report_zero_norm(self, rng):
        with launch(1, 1, codec="bf16") as (servers, (client,), threads):
            client.start(np.ones(8, np.float32), np.zeros(8, np.float32))
            assert client.residual_norm() == 0.0
            client.stop()
            join_all(threads)

    def test_quantized_dtype_guard(self):
        router = LocalRouter(2)
        client = ParamClient(1, [0], router.endpoint(1), codec="int8")
        with pytest.raises(ValueError, match="float32"):
            client.start(np.zeros(8, np.float64), np.zeros(8, np.float64))


class TestPumpTaskNaming:
    def test_pump_name_refreshes_per_op(self, rng):
        """The pump task must be renamed per dequeued op — a stale
        spawn-time name misattributes later ops in error output."""
        router = LocalRouter(2, delay=2)  # ops span scheduler steps
        server = ParamServer(0, [1], router.endpoint(0))
        t = threading.Thread(target=server.start, daemon=True)
        t.start()
        try:
            client = ParamClient(1, [0], router.endpoint(1), seed_servers=True)
            w0 = rng.normal(size=8).astype(np.float32)
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            names = set()
            client.async_send_grad()
            client.async_recv_param()
            task = client._pump_task[0]
            while client.sched.queue:
                names.add(task.name)
                client.ping()
            assert "pump:0:send_grad" in names
            assert "pump:0:recv_param" in names
            client.stop()
            join_all([t])
        finally:
            server.live.stop()


class TestServerCheckpointResume:
    def test_periodic_hook_writes_during_serve(self, rng, tmp_path):
        """ckpt_dir + tiny interval: snapshots land while serving, plus a
        final one at stop; the file restores cleanly."""
        from mpit_tpu.utils.checkpoint import load_server_state

        w0 = rng.normal(size=8).astype(np.float32)
        n = 2
        router = LocalRouter(n)
        server = ParamServer(
            0, [1], router.endpoint(0), rule="add",
            ckpt_dir=tmp_path, ckpt_interval=0.02,
        )
        thread = threading.Thread(target=server.start, daemon=True)
        thread.start()
        try:
            client = ParamClient(1, [0], router.endpoint(1), seed_servers=True)
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            for i in range(4):
                grad[:] = i + 1.0
                client.async_send_grad()
                client.wait()
                time.sleep(0.03)  # let the interval elapse between applies
            client.stop()
            join_all([thread])
        finally:
            server.live.stop()
        assert server.ckpts_written >= 2  # periodic + final
        offset, size, param_ck, _state, meta = load_server_state(
            tmp_path / "server0_latest.npz"
        )
        assert (offset, size) == (0, 8)
        assert meta["grads_applied"] == 4
        np.testing.assert_allclose(param_ck, w0 + 1 + 2 + 3 + 4, rtol=1e-6)

    def test_adam_resume_matches_uninterrupted(self, rng, tmp_path):
        """Save server shard state mid-training, restart the topology from
        the checkpoint, finish — result must match a never-interrupted
        rollout (moments included; the reference loses these, SURVEY §5)."""
        w0 = rng.normal(size=10).astype(np.float32)
        grads = [rng.normal(size=10).astype(np.float32) for _ in range(4)]
        hp = dict(lr=1e-2, beta1=0.9, beta2=0.999, epsilon=1e-8)

        # Session 1: seed + 2 grads, checkpoint both servers, stop.
        paths = []
        with launch(2, 1, rule=rules.make("adam", **hp)) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            for g in grads[:2]:
                grad[:] = g
                client.async_send_grad()
                client.wait()
            client.stop()
            join_all(threads)
            paths = [s.save_state(tmp_path) for s in servers]

        # Session 2: restore servers, client joins WITHOUT seeding, 2 more
        # grads, pull final params.
        router = __import__("mpit_tpu.comm.local", fromlist=["LocalRouter"]).LocalRouter(3)
        servers2 = [
            ParamServer(r, [2], router.endpoint(r), rule=rules.make("adam", **hp))
            for r in (0, 1)
        ]
        for s, p in zip(servers2, paths):
            s.restore_state(p)
        threads2 = [threading.Thread(target=s.start, daemon=True) for s in servers2]
        for t in threads2:
            t.start()
        client2 = ParamClient(2, [0, 1], router.endpoint(2), seed_servers=False)
        param2, grad2 = np.zeros_like(w0), np.zeros_like(w0)
        client2.start(param2, grad2)
        for g in grads[2:]:
            grad2[:] = g
            client2.async_send_grad()
            client2.wait()
        client2.async_recv_param()
        client2.wait()
        client2.stop()
        join_all(threads2)

        # Uninterrupted reference rollout.
        rule = rules.make("adam", **hp)
        p = jnp.asarray(w0)
        st = rule.init(p)
        for g in grads:
            p, st = rule.apply(p, jnp.asarray(g), st)
        np.testing.assert_allclose(param2, np.asarray(p), rtol=1e-6, atol=1e-7)

    def test_restore_after_init_rejected(self, rng, tmp_path):
        w0 = rng.normal(size=6).astype(np.float32)
        with launch(1, 1) as (servers, (client,), threads):
            client.start(w0.copy(), np.zeros_like(w0))
            path = None
            with pytest.raises(RuntimeError):
                servers[0].restore_state(tmp_path / "nope.npz")
            path = servers[0].save_state(tmp_path)
            client.stop()
            join_all(threads)
        assert path and "server0" in path

    def test_resume_with_seeding_client_warns_not_hangs(self, rng, tmp_path):
        """A resume client mistakenly wired with seed_servers=True must not
        deadlock: the restored server consumes+acks the push (client
        authoritative for params, optimizer state kept)."""
        w0 = rng.normal(size=8).astype(np.float32)
        with launch(1, 1, rule=rules.make("adam")) as (servers, (client,), threads):
            param, grad = w0.copy(), np.zeros_like(w0)
            client.start(param, grad)
            grad[:] = 1.0
            client.async_send_grad()
            client.wait()
            client.stop()
            join_all(threads)
            path = servers[0].save_state(tmp_path)

        router = __import__("mpit_tpu.comm.local", fromlist=["LocalRouter"]).LocalRouter(2)
        server2 = ParamServer(0, [1], router.endpoint(0), rule=rules.make("adam"))
        server2.restore_state(path)
        t = threading.Thread(target=server2.start, daemon=True)
        t.start()
        client2 = ParamClient(1, [0], router.endpoint(1), seed_servers=True)
        fresh = rng.normal(size=8).astype(np.float32)
        param2, grad2 = fresh.copy(), np.zeros_like(w0)
        client2.start(param2, grad2)  # would hang before the guard
        client2.async_recv_param()
        client2.wait()
        np.testing.assert_allclose(param2, fresh, rtol=1e-6)  # client's seed won
        assert server2.grads_applied == 1  # counter restored from meta
        client2.stop()
        join_all([t])
