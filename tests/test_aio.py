"""Tests for the L1 async engine: Queue, Scheduler, aio_send/aio_recv.

Coverage model follows the reference's semantics (queue.lua FIFO behavior,
init.lua scheduler round-robin, cancel-on-shutdown) but as real assertions
rather than eyeballed prints (SURVEY.md section 4).
"""

import pytest

from mpit_tpu.aio import (
    DONE,
    EXEC,
    LiveFlag,
    Queue,
    Scheduler,
    TaskError,
    aio_recv,
    aio_send,
)


class TestQueue:
    def test_fifo_order(self):
        q = Queue()
        for i in range(5):
            q.push(i)
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]

    def test_pop_empty_returns_none(self):
        assert Queue().pop() is None

    def test_len_and_bool(self):
        q = Queue()
        assert not q and len(q) == 0
        q.push("x")
        assert q and len(q) == 1

    def test_interleaved(self):
        q = Queue()
        q.push(1)
        q.push(2)
        assert q.pop() == 1
        q.push(3)
        assert q.pop() == 2
        assert q.pop() == 3


class TestScheduler:
    def test_spawn_runs_to_completion(self):
        sched = Scheduler()
        log = []

        def work():
            for i in range(3):
                log.append(i)
                yield EXEC

        task = sched.spawn(work(), name="w")
        sched.wait()
        assert task.state == DONE
        assert log == [0, 1, 2]

    def test_round_robin_interleaves(self):
        sched = Scheduler()
        log = []

        def work(tag, n):
            for i in range(n):
                log.append((tag, i))
                yield EXEC

        sched.spawn(work("a", 2))
        sched.spawn(work("b", 2))
        sched.wait()
        # Spawn primes one step each, then round-robin alternates.
        assert log == [("a", 0), ("b", 0), ("a", 1), ("b", 1)]

    def test_return_value_captured(self):
        sched = Scheduler()

        def work():
            yield EXEC
            return 42

        task = sched.spawn(work())
        assert sched.wait_for(task) == 42

    def test_immediate_completion(self):
        sched = Scheduler()

        def work():
            return "done"
            yield  # pragma: no cover

        task = sched.spawn(work())
        assert task.state == DONE
        assert task.result == "done"
        assert len(sched) == 0

    def test_error_propagates_from_wait(self):
        sched = Scheduler()

        def boom():
            yield EXEC
            raise ValueError("boom")

        sched.spawn(boom(), name="boom")
        with pytest.raises(TaskError) as excinfo:
            sched.wait()
        assert isinstance(excinfo.value.cause, ValueError)

    def test_ping_single_steps(self):
        sched = Scheduler()
        log = []

        def work():
            log.append("a")
            yield EXEC
            log.append("b")

        sched.spawn(work())  # primes: runs to first yield
        assert log == ["a"]
        sched.ping()
        assert log == ["a", "b"]
        assert len(sched) == 0

    def test_wait_deadline(self):
        sched = Scheduler()

        def forever():
            while True:
                yield EXEC

        sched.spawn(forever())
        with pytest.raises(TimeoutError):
            sched.wait(deadline=0.05)

    def test_on_done_callback(self):
        sched = Scheduler()
        seen = []

        def work():
            yield EXEC
            return 7

        sched.spawn(work(), on_done=lambda t: seen.append(t.result))
        sched.wait()
        assert seen == [7]


class FakeTransport:
    """Scripted transport: messages become visible/complete after N polls."""

    def __init__(self, send_delay=2, recv_delay=2):
        self.send_delay = send_delay
        self.recv_delay = recv_delay
        self.mailbox = {}
        self.cancelled = []
        self._handles = {}
        self._next = 0

    def isend(self, data, dst, tag):
        handle = self._next
        self._next += 1
        self._handles[handle] = {"polls": 0, "data": data, "dst": dst, "tag": tag}
        return handle

    def irecv(self, src, tag, out=None):
        handle = self._next
        self._next += 1
        self._handles[handle] = {"polls": 0, "data": self.mailbox[(src, tag)]}
        return handle

    def iprobe(self, src, tag):
        entry = self.mailbox.get((src, tag))
        if entry is None:
            return False
        probe = self._handles.setdefault(("probe", src, tag), {"polls": 0})
        probe["polls"] += 1
        return probe["polls"] > self.recv_delay

    def test(self, handle):
        info = self._handles[handle]
        info["polls"] += 1
        if info["polls"] > self.send_delay:
            if "dst" in info:
                self.mailbox[(info["dst"], info["tag"])] = info["data"]
            return True
        return False

    def cancel(self, handle):
        self.cancelled.append(handle)

    def payload(self, handle):
        return self._handles[handle]["data"]


class TestAioTransfers:
    def test_send_then_recv(self):
        transport = FakeTransport()
        sched = Scheduler()
        got = []
        sched.spawn(aio_send(transport, b"hello", dst=1, tag=3), name="send")
        recv = sched.spawn(
            aio_recv(transport, src=1, tag=3, cb=got.append), name="recv"
        )
        sched.wait()
        assert got == [b"hello"]
        assert recv.result == b"hello"

    def test_send_cancelled_on_stop(self):
        transport = FakeTransport(send_delay=10**9)
        sched = Scheduler()
        live = LiveFlag()
        sched.spawn(aio_send(transport, b"x", dst=0, tag=1, live=live))
        for _ in range(3):
            sched.ping()
        live.stop()
        sched.wait()
        assert transport.cancelled  # in-flight send released (reference README:71)

    def test_recv_cancelled_while_probing(self):
        transport = FakeTransport()  # nothing ever arrives
        sched = Scheduler()
        live = LiveFlag()
        task = sched.spawn(aio_recv(transport, src=0, tag=1, live=live))
        sched.ping()
        live.stop()
        sched.wait()
        assert task.state == DONE
        assert task.result is None
